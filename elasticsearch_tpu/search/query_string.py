"""query_string / simple_query_string — reduced Lucene query grammar.

Reference: QueryStringQueryParser / SimpleQueryStringParser
(core/index/query/). Supported grammar subset:

    term term2              → OR of match terms (default_operator applies)
    "a phrase"              → match_phrase
    field:term              → match on that field
    field:"a phrase"        → phrase on that field
    +term / -term           → must / must_not
    term AND term2          → must
    term OR term2           → should
    NOT term                → must_not
    field:[a TO b]          → range (inclusive); {a TO b} exclusive

Parsed into the same AST the structured DSL uses.
"""

from __future__ import annotations

import re

from elasticsearch_tpu.common.errors import QueryParsingError
from elasticsearch_tpu.search.query_dsl import (
    BoolQuery, MatchAllQuery, MatchPhraseQuery, MatchQuery, Query,
    RangeQuery, WildcardQuery)

_TOKEN_RE = re.compile(
    r"""\s*(?:
        (?P<op>AND|OR|NOT)\b
      | (?P<mod>[+-])?
        (?:(?P<field>[\w.*]+):)?
        (?:
            "(?P<phrase>[^"]*)"
          | (?P<rng>[\[{][^\]}]*\s+TO\s+[^\]}]*[\]}])
          | (?P<term>[^\s"]+)
        )
    )""", re.VERBOSE)


def _leaf(field: str | None, phrase: str | None, rng: str | None,
          term: str | None, default_field: str,
          analyzer: str | None = None,
          lowercase_expanded: bool = True) -> Query:
    f = field or default_field
    if phrase is not None:
        return MatchPhraseQuery(field=f, text=phrase, analyzer=analyzer)
    if term and ("*" in term or "?" in term):
        # expanded (wildcard) terms bypass analysis; Lucene's
        # lowercase_expanded_terms (default true) lowercases the pattern
        pat = term.lower() if lowercase_expanded else term
        return WildcardQuery(field=f, pattern=pat)
    if rng is not None:
        inc_lo, inc_hi = rng[0] == "[", rng[-1] == "]"
        lo, hi = re.split(r"\s+TO\s+", rng[1:-1].strip())
        def parse_bound(s):
            if s == "*":
                return None
            try:
                return float(s)
            except ValueError:
                return s
        q = RangeQuery(field=f)
        if inc_lo:
            q.gte = parse_bound(lo)
        else:
            q.gt = parse_bound(lo)
        if inc_hi:
            q.lte = parse_bound(hi)
        else:
            q.lt = parse_bound(hi)
        return q
    return MatchQuery(field=f, text=term or "", analyzer=analyzer)


def parse_query_string(qbody: dict) -> Query:
    qs = str(qbody.get("query", ""))
    default_field = qbody.get("default_field", qbody.get("fields", ["*"])[0]
                              if qbody.get("fields") else "*")
    if default_field.endswith("^0") or "^" in default_field:
        default_field = default_field.split("^")[0]
    default_op = str(qbody.get("default_operator", "or")).lower()
    analyzer = qbody.get("analyzer")
    lowercase_expanded = qbody.get("lowercase_expanded_terms", True)
    if isinstance(lowercase_expanded, str):
        lowercase_expanded = lowercase_expanded.lower() != "false"

    must: list[Query] = []
    should: list[Query] = []
    must_not: list[Query] = []
    pending_op: str | None = None
    negate_next = False

    pos = 0
    any_token = False
    while pos < len(qs):
        m = _TOKEN_RE.match(qs, pos)
        if not m or m.end() == pos:
            break
        pos = m.end()
        if m.group("op"):
            op = m.group("op")
            if op == "NOT":
                negate_next = True
            else:
                pending_op = op
            continue
        any_token = True
        leaf = _leaf(m.group("field"), m.group("phrase"), m.group("rng"),
                     m.group("term"), default_field, analyzer,
                     lowercase_expanded)
        mod = m.group("mod")
        if negate_next or mod == "-":
            must_not.append(leaf)
            negate_next = False
        elif mod == "+" or pending_op == "AND" or \
                (pending_op is None and default_op == "and"):
            # AND binds the previous should-clause too (approximation of
            # Lucene precedence: a AND b → both must)
            if pending_op == "AND" and should:
                must.append(should.pop())
            must.append(leaf)
        else:
            should.append(leaf)
        pending_op = None

    if not any_token:
        if qs.strip():
            raise QueryParsingError(f"could not parse query_string [{qs}]")
        return MatchAllQuery()
    if len(should) == 1 and not must and not must_not:
        return should[0]
    return BoolQuery(must=must, should=should, must_not=must_not)
