"""Script engine: a safe, compilable expression language over doc values.

Plays the role of the reference's ScriptService + lang-expression plugin
(core/script/ScriptService.java:227; plugins/lang-expression — the engine
BASELINE.json's configs name for script_score): expressions compile once and
evaluate **vectorized over all docs** as jnp ops — no per-doc interpreter.

Grammar: Python expression syntax restricted to arithmetic/comparison ops,
math functions, and the ES script bindings:

    doc['field'].value        → the field's doc-values column
    _score                    → the query score vector
    params.x / params['x']    → request-supplied constants
    cosineSimilarity(params.qv, 'field')   → vector similarity (+ dotProduct)
    log/log10/sqrt/abs/exp/min/max/pow/sigmoid/floor/ceil

Compiled via the Python ``ast`` module with a strict whitelist (the sandbox
the reference gets from Lucene expressions' closed grammar).
"""

from __future__ import annotations

import ast as _pyast
from typing import Any, Callable

import jax.numpy as jnp

from elasticsearch_tpu.common.errors import QueryParsingError, IllegalArgumentError

_ALLOWED_BINOPS = {
    _pyast.Add: lambda a, b: a + b,
    _pyast.Sub: lambda a, b: a - b,
    _pyast.Mult: lambda a, b: a * b,
    _pyast.Div: lambda a, b: a / b,
    _pyast.Mod: lambda a, b: a % b,
    _pyast.Pow: lambda a, b: a ** b,
}
_ALLOWED_CMPOPS = {
    _pyast.Gt: lambda a, b: a > b, _pyast.GtE: lambda a, b: a >= b,
    _pyast.Lt: lambda a, b: a < b, _pyast.LtE: lambda a, b: a <= b,
    _pyast.Eq: lambda a, b: a == b, _pyast.NotEq: lambda a, b: a != b,
}

def _fold(fn, args):
    from functools import reduce
    if len(args) == 1:
        return args[0]
    return reduce(fn, args)


_FUNCS: dict[str, Callable] = {
    "log": jnp.log, "ln": jnp.log, "log10": jnp.log10, "sqrt": jnp.sqrt,
    "abs": jnp.abs, "exp": jnp.exp, "floor": jnp.floor, "ceil": jnp.ceil,
    # variadic like the builtins, elementwise like jnp (folded pairwise)
    "min": lambda *a: _fold(jnp.minimum, a),
    "max": lambda *a: _fold(jnp.maximum, a),
    "pow": jnp.power,
    "sigmoid": lambda x, k=1.0, a=1.0: x ** a / (x ** a + k ** a),
    "saturation": lambda x, k: x / (x + k),
}


class ScriptContext:
    """Per-segment evaluation context handed to compiled scripts."""

    def __init__(self, get_numeric_column, get_vector_column, scores,
                 params: dict, variables: dict | None = None):
        self.get_numeric_column = get_numeric_column   # field → ([N] f32, exists)
        self.get_vector_column = get_vector_column     # field → ([N, D] f32, exists)
        self.scores = scores                           # [N] f32
        self.params = params
        # bare-name bindings (bucket_script/bucket_selector buckets_path
        # values) — resolved before the _score special name
        self.variables = variables or {}


class CompiledScript:
    def __init__(self, source: str):
        self.source = source
        try:
            tree = _pyast.parse(source, mode="eval")
        except SyntaxError as e:
            raise QueryParsingError(f"script compile error: {e}") from None
        self._tree = tree

    def evaluate(self, ctx: ScriptContext):
        return _eval(self._tree.body, ctx)

    def vector_fields(self) -> set | None:
        """Plan-time scan: the vector fields this script's accessors
        (cosineSimilarity / dotProduct) read. Drives traced-input
        tree-shaking — a numeric-only script must not force multi-GB
        vector columns into the compiled program. Returns the (possibly
        empty) set of constant field names, or None when a field argument
        is not a literal (caller must assume all vector columns)."""
        out: set = set()
        for node in _pyast.walk(self._tree):
            if isinstance(node, _pyast.Call) and \
                    isinstance(node.func, _pyast.Name) and \
                    node.func.id in ("cosineSimilarity", "dotProduct"):
                if len(node.args) == 2 and \
                        isinstance(node.args[1], _pyast.Constant) and \
                        isinstance(node.args[1].value, str):
                    out.add(node.args[1].value)
                else:
                    return None
        return out

    def uses_vectors(self) -> bool:
        fields = self.vector_fields()
        return fields is None or bool(fields)


def _eval(node: _pyast.AST, ctx: ScriptContext) -> Any:  # noqa: C901
    if isinstance(node, _pyast.Constant):
        if isinstance(node.value, (int, float, str)):
            return node.value
        raise QueryParsingError(f"script constant not allowed: {node.value!r}")
    if isinstance(node, _pyast.Name):
        if node.id in ctx.variables:
            return ctx.variables[node.id]
        if node.id == "_score":
            return ctx.scores
        raise QueryParsingError(f"unknown script variable [{node.id}]")
    if isinstance(node, _pyast.BinOp):
        op = _ALLOWED_BINOPS.get(type(node.op))
        if op is None:
            raise QueryParsingError("operator not allowed in script")
        return op(_eval(node.left, ctx), _eval(node.right, ctx))
    if isinstance(node, _pyast.UnaryOp):
        if isinstance(node.op, _pyast.USub):
            return -_eval(node.operand, ctx)
        if isinstance(node.op, _pyast.Not):
            return jnp.logical_not(_eval(node.operand, ctx))
        raise QueryParsingError("unary operator not allowed in script")
    if isinstance(node, _pyast.Compare):
        left = _eval(node.left, ctx)
        result = None
        for cmp_op, comp in zip(node.ops, node.comparators):
            op = _ALLOWED_CMPOPS.get(type(cmp_op))
            if op is None:
                raise QueryParsingError("comparison not allowed in script")
            right = _eval(comp, ctx)
            piece = op(left, right)
            result = piece if result is None else \
                jnp.logical_and(result, piece)
            left = right
        return result
    if isinstance(node, _pyast.BoolOp):
        fold = jnp.logical_and if isinstance(node.op, _pyast.And) \
            else jnp.logical_or
        out = _eval(node.values[0], ctx)
        for v in node.values[1:]:
            out = fold(out, _eval(v, ctx))
        return out
    if isinstance(node, _pyast.IfExp):
        cond = _eval(node.test, ctx)
        return jnp.where(cond, _eval(node.body, ctx), _eval(node.orelse, ctx))
    if isinstance(node, _pyast.Subscript):
        # doc['field'] and params['x']
        base = node.value
        key_node = node.slice
        if isinstance(key_node, _pyast.Constant):
            key = key_node.value
        else:
            raise QueryParsingError("subscript must be a literal")
        if isinstance(base, _pyast.Name) and base.id == "doc":
            return _DocField(str(key))
        if isinstance(base, _pyast.Name) and base.id == "params":
            return _param(ctx, str(key))
        raise QueryParsingError("only doc[...] / params[...] subscripts allowed")
    if isinstance(node, _pyast.Attribute):
        base = _eval(node.value, ctx) if not (
            isinstance(node.value, _pyast.Name) and node.value.id == "params") \
            else None
        if isinstance(node.value, _pyast.Name) and node.value.id == "params":
            return _param(ctx, node.attr)
        if isinstance(base, _DocField) and node.attr == "value":
            col, exists = ctx.get_numeric_column(base.field)
            return jnp.where(exists, col, 0.0)
        if isinstance(base, _DocField) and node.attr == "empty":
            _, exists = ctx.get_numeric_column(base.field)
            return ~exists
        raise QueryParsingError(f"unknown attribute [{node.attr}]")
    if isinstance(node, _pyast.Call):
        if not isinstance(node.func, _pyast.Name):
            raise QueryParsingError("only plain function calls allowed")
        fname = node.func.id
        if fname in ("cosineSimilarity", "dotProduct"):
            if len(node.args) != 2:
                raise QueryParsingError(f"{fname} expects (query_vector, 'field')")
            qv = _eval(node.args[0], ctx)
            fld = node.args[1]
            if not (isinstance(fld, _pyast.Constant) and isinstance(fld.value, str)):
                raise QueryParsingError(f"{fname} field must be a string literal")
            vecs, exists = ctx.get_vector_column(fld.value)
            q = jnp.asarray(qv, dtype=jnp.float32)
            if fname == "cosineSimilarity":
                qn = q / jnp.sqrt((q * q).sum() + 1e-12)
                # vecs rows are L2-normalized at reader build
                return jnp.where(exists, vecs @ qn, 0.0)
            return jnp.where(exists, vecs @ q, 0.0)
        fn = _FUNCS.get(fname)
        if fn is None:
            raise QueryParsingError(f"unknown script function [{fname}]")
        args = [_eval(a, ctx) for a in node.args]
        return fn(*args)
    raise QueryParsingError(
        f"script syntax not allowed: {type(node).__name__}")


class _DocField:
    def __init__(self, field: str):
        self.field = field


def _param(ctx: ScriptContext, key: str):
    if key not in ctx.params:
        raise IllegalArgumentError(f"missing script param [{key}]")
    return ctx.params[key]


_SCRIPT_CACHE: dict[str, CompiledScript] = {}


def compile_script(source: str) -> CompiledScript:
    """Compile+cache (reference: ScriptService compilation cache,
    core/script/ScriptService.java:269-310)."""
    cs = _SCRIPT_CACHE.get(source)
    if cs is None:
        cs = CompiledScript(source)
        _SCRIPT_CACHE[source] = cs
    return cs
