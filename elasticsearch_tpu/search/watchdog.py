"""Dispatch watchdog — stall detection for device waits (the hang
half of the fault model; the raise half is the PlaneBreaker's).

A device dispatch that simply *hangs* (wedged XLA program, stuck H2D
transfer, runaway compile) raises nothing: no breaker trips, and every
thread blocked on it is wedged too. This module makes the hang
observable and bounded. Every device wait on the scheduler's workers
registers here — (site, lane, shape_key, n_real, trace/task ids,
monotonic start) — and a monitor thread compares each wait's age
against its **predicted envelope**: ``costs.estimate(lane, shape_key)``
(the PR 15 cost observatory) × ``stall_multiplier``, bounded to
[``floor_s``, ``ceiling_s``]; a shape the cost table has never seen
gets the larger ``cold_floor_s`` (a cold shape legitimately includes a
trace+compile).

The escalation ladder, per overdue wait:

1. a ``dispatch-stall`` flight-recorder event (joinable back to the
   request's trace/task ids);
2. the *wait* is abandoned via the registrant's ``on_stall`` callback
   with a typed :class:`~elasticsearch_tpu.search.jit_exec.
   DeviceStallError`. HONESTY: Python cannot cancel a wedged XLA
   dispatch — the program may still own the device; the wedged worker
   thread is left to finish (or not) while its waiters fail over;
3. the error feeds :func:`~elasticsearch_tpu.search.jit_exec.
   note_device_error` → the PlaneBreaker counts it toward a trip, and
   the request fails over with registered reason ``device-stall``;
4. after ``quarantine_stalls`` CONSECUTIVE stalls: **quarantine** — the
   breaker is held open unconditionally (no half-open probe on live
   traffic) and reopen is gated on a tiny background *probe program*
   (:func:`~elasticsearch_tpu.search.jit_exec.run_probe_program`,
   routed through the same fault seam as live traffic) completing.

Like the PlaneBreaker, the module singleton :data:`dispatch_watchdog`
IS the per-node watchdog: all in-process nodes share one device (one
node = one process = one device in deployment); ``search.watchdog.*``
node settings configure it via :func:`settings_for`.
"""

from __future__ import annotations

import threading
import time

from elasticsearch_tpu.observability.context import current_node_id


class WaitEntry:
    """One registered device wait. Identity object — state transitions
    (completed/abandoned) are guarded by the watchdog's lock."""

    __slots__ = ("site", "lane", "shape_key", "n_real", "node_id",
                 "trace_id", "task_id", "started", "budget_s",
                 "on_stall", "stalled", "done")

    def __init__(self, site, lane, shape_key, n_real, node_id,
                 trace_id, task_id, started, budget_s, on_stall):
        self.site = site
        self.lane = lane
        self.shape_key = shape_key
        self.n_real = n_real
        self.node_id = node_id
        self.trace_id = trace_id
        self.task_id = task_id
        self.started = started          # monotonic (perf_counter)
        self.budget_s = budget_s
        self.on_stall = on_stall
        self.stalled = False
        self.done = False


def _context_ids() -> tuple:
    """(trace_id, task_id) of the registering thread, best-effort — the
    join keys the dispatch-stall event carries so a stall on the
    monitor thread still points back at the wedged request."""
    trace_id = task_id = None
    try:
        from elasticsearch_tpu.observability import tracing
        ctx = tracing.current_ctx()
        if ctx is not None:
            trace_id = ctx.trace_id
    except Exception:                   # noqa: BLE001 — best-effort join
        pass
    try:
        from elasticsearch_tpu.tasks import current_task
        task = current_task()
        if task is not None:
            task_id = task.task_id
    except Exception:                   # noqa: BLE001 — best-effort join
        pass
    return trace_id, task_id


class DispatchWatchdog:
    """Per-node stall watchdog over registered device waits (module
    singleton :data:`dispatch_watchdog` — see module docstring)."""

    def __init__(self, enabled: bool = True,
                 stall_multiplier: float = 20.0,
                 floor_s: float = 10.0, cold_floor_s: float = 30.0,
                 ceiling_s: float = 120.0, quarantine_stalls: int = 3,
                 tick_s: float = 0.05, probe_interval_s: float = 0.5,
                 probe_budget_s: float = 30.0):
        self._lock = threading.Lock()
        self.enabled = bool(enabled)
        self.stall_multiplier = float(stall_multiplier)
        self.floor_s = float(floor_s)
        self.cold_floor_s = float(cold_floor_s)
        self.ceiling_s = float(ceiling_s)
        self.quarantine_stalls = max(int(quarantine_stalls), 1)
        self.tick_s = float(tick_s)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_budget_s = float(probe_budget_s)
        self._entries: list[WaitEntry] = []
        self._consecutive_stalls = 0
        self._monitor: threading.Thread | None = None
        self._probe: threading.Thread | None = None
        self._probe_started = 0.0
        self._probe_outcome: list = []
        self._next_probe_at = 0.0
        # local tallies (the jit_exec counters are the exported truth;
        # these feed _nodes/stats.watchdog per instance)
        self.stalls = 0
        self.abandoned = 0
        self.quarantines = 0
        self.probe_reopens = 0
        self.probes_attempted = 0

    # ---- configuration -----------------------------------------------------

    def configure(self, *, enabled=None, stall_multiplier=None,
                  floor_s=None, cold_floor_s=None, ceiling_s=None,
                  quarantine_stalls=None, tick_s=None,
                  probe_interval_s=None, probe_budget_s=None) -> None:
        """Apply node settings (None leaves a knob unchanged)."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if stall_multiplier is not None:
                self.stall_multiplier = float(stall_multiplier)
            if floor_s is not None:
                self.floor_s = float(floor_s)
            if cold_floor_s is not None:
                self.cold_floor_s = float(cold_floor_s)
            if ceiling_s is not None:
                self.ceiling_s = float(ceiling_s)
            if quarantine_stalls is not None:
                self.quarantine_stalls = max(int(quarantine_stalls), 1)
            if tick_s is not None:
                self.tick_s = float(tick_s)
            if probe_interval_s is not None:
                self.probe_interval_s = float(probe_interval_s)
            if probe_budget_s is not None:
                self.probe_budget_s = float(probe_budget_s)

    def budget_s(self, lane: str | None, shape_key=None) -> float:
        """The stall envelope for one wait: the cost observatory's
        estimate × the multiplier, floor/ceiling-bounded; a shape with
        no estimate gets the cold floor (its first wait legitimately
        includes a trace+compile)."""
        est_us = None
        if lane is not None:
            try:
                from elasticsearch_tpu.observability import costs
                est_us = costs.estimate(lane, shape_key)
            except Exception:           # noqa: BLE001 — never block dispatch
                est_us = None
        if est_us is None:
            return max(self.cold_floor_s, self.floor_s)
        budget = (float(est_us) / 1e6) * self.stall_multiplier
        return min(max(budget, self.floor_s), self.ceiling_s)

    # ---- registration ------------------------------------------------------

    def register(self, site: str, lane: str | None = None,
                 shape_key=None, n_real: int = 0,
                 on_stall=None) -> WaitEntry | None:
        """Register one device wait starting NOW → its entry (None when
        the watchdog is disabled). ``on_stall(err)`` runs on the monitor
        thread when the wait outlives its envelope — it must abandon the
        wait's *bookkeeping* (resolve waiters, release slots), never try
        to interrupt the wedged thread."""
        if not self.enabled:
            return None
        trace_id, task_id = _context_ids()
        entry = WaitEntry(site, lane, shape_key, int(n_real),
                          current_node_id(), trace_id, task_id,
                          time.perf_counter(),
                          self.budget_s(lane, shape_key), on_stall)
        with self._lock:
            self._entries.append(entry)
            self._ensure_monitor_locked()
        return entry

    def complete(self, entry: WaitEntry | None) -> bool:
        """The wait finished: deregister → True, or False when the
        monitor already abandoned it (the caller's results belong to a
        failed-over request — discard, don't deliver)."""
        if entry is None:
            return True
        with self._lock:
            entry.done = True
            try:
                self._entries.remove(entry)
            except ValueError:
                pass
            if entry.stalled:
                return False
            self._consecutive_stalls = 0
            return True

    # ---- monitor -----------------------------------------------------------

    def _ensure_monitor_locked(self) -> None:
        if self._monitor is None or not self._monitor.is_alive():
            t = threading.Thread(target=self._monitor_loop, daemon=True,
                                 name="dispatch-watchdog")
            self._monitor = t
            t.start()

    def _monitor_loop(self) -> None:
        while True:
            time.sleep(self.tick_s)
            try:
                self._tick()
            except Exception:           # noqa: BLE001 — the watchdog must
                pass                    # outlive any telemetry error

    def _tick(self) -> None:
        from elasticsearch_tpu.search import jit_exec
        now = time.perf_counter()
        overdue: list[WaitEntry] = []
        quarantine = False
        with self._lock:
            for entry in self._entries:
                if entry.stalled or entry.done:
                    continue
                if now - entry.started > entry.budget_s:
                    entry.stalled = True
                    overdue.append(entry)
            if overdue:
                self._entries = [e for e in self._entries
                                 if not e.stalled]
                self._consecutive_stalls += len(overdue)
                self.stalls += len(overdue)
                self.abandoned += len(overdue)
                if self._consecutive_stalls >= self.quarantine_stalls \
                        and not jit_exec.plane_breaker.quarantined:
                    quarantine = True
                    self.quarantines += 1
        for entry in overdue:
            self._escalate(entry, now)
        if quarantine:
            self._enter_quarantine()
        # probing the process-global breaker is the SINGLETON's job
        # alone: a secondary instance (tests build them) must never
        # race its own probe/reopen against the per-node watchdog's
        if jit_exec.plane_breaker.quarantined and \
                globals().get("dispatch_watchdog") is self:
            self._probe_step(now)

    def _escalate(self, entry: WaitEntry, now: float) -> None:
        """Rungs 1-3 of the ladder for one overdue wait: flight-record,
        abandon via ``on_stall``, feed the breaker."""
        from elasticsearch_tpu.observability import flightrec
        from elasticsearch_tpu.search import jit_exec
        waited = now - entry.started
        err = jit_exec.DeviceStallError(
            f"device wait stalled at site [{entry.site}] lane "
            f"[{entry.lane}]: {waited:.3f}s exceeds the "
            f"{entry.budget_s:.3f}s envelope; wait abandoned (the "
            f"program may still own the device)")
        attrs = {"site": entry.site, "lane": entry.lane,
                 "n_real": entry.n_real,
                 "wait_seconds": round(waited, 3),
                 "budget_seconds": round(entry.budget_s, 3)}
        if entry.shape_key is not None:
            attrs["shape_key"] = str(entry.shape_key)[:120]
        if entry.trace_id is not None:
            attrs["trace_id"] = entry.trace_id
        if entry.task_id is not None:
            attrs["task_id"] = entry.task_id
        flightrec.note("dispatch-stall", node_id=entry.node_id or "",
                       **attrs)
        jit_exec.note_watchdog_stall()
        jit_exec.note_device_error(err)
        jit_exec.note_watchdog_abandoned()
        if entry.on_stall is not None:
            try:
                entry.on_stall(err)
            except Exception:           # noqa: BLE001 — an abandon-callback
                pass                    # bug must not kill the monitor

    # ---- quarantine + probe ------------------------------------------------

    def _enter_quarantine(self) -> None:
        from elasticsearch_tpu.observability import flightrec
        from elasticsearch_tpu.search import jit_exec
        jit_exec.plane_breaker.quarantine()
        jit_exec.note_watchdog_quarantine()
        flightrec.note("quarantine", phase="enter",
                       consecutive_stalls=self._consecutive_stalls,
                       threshold=self.quarantine_stalls)
        with self._lock:
            self._next_probe_at = 0.0   # probe immediately
            # a stale outcome from an earlier quarantine round must not
            # satisfy this one — only a FRESH probe completion reopens
            # (a still-wedged old probe thread appends to its own list)
            self._probe_outcome = []

    def _probe_step(self, now: float) -> None:
        """One monitor-tick of the probe loop: keep at most ONE probe
        outstanding (a wedged probe thread is left to finish — spawning
        more would stack wedged threads), and on a completed successful
        probe release the quarantine."""
        from elasticsearch_tpu.observability import flightrec
        from elasticsearch_tpu.search import jit_exec
        with self._lock:
            probe = self._probe
            if probe is not None and probe.is_alive() and \
                    now - self._probe_started <= self.probe_budget_s:
                return                  # outstanding, within its budget
            # a probe alive past probe_budget_s is itself wedged: give
            # up WAITING on it (the thread is left to finish or not —
            # same honesty as every abandon) and allow a fresh one; the
            # old thread appends to its own superseded outcome list, so
            # a late completion cannot satisfy a newer round
            outcome = self._probe_outcome
            if outcome and outcome[0] == "ok":
                self._probe = None
                self._probe_outcome = []
                self._consecutive_stalls = 0
                self.probe_reopens += 1
                reopen = True
            else:
                reopen = False
                if now < self._next_probe_at:
                    return
                self._next_probe_at = now + self.probe_interval_s
                self._probe_outcome = outcome = []

                def _run_probe(out=outcome):
                    try:
                        jit_exec.run_probe_program()
                        out.append("ok")
                    except Exception:   # noqa: BLE001 — a failed probe
                        out.append("error")   # just keeps quarantine

                t = threading.Thread(target=_run_probe, daemon=True,
                                     name="watchdog-probe")
                self._probe = t
                self._probe_started = now
                self.probes_attempted += 1
        if reopen:
            jit_exec.plane_breaker.release_quarantine()
            jit_exec.note_watchdog_probe_reopen()
            flightrec.note("quarantine", phase="probe-reopen",
                           probes_attempted=self.probes_attempted)
            return
        t.start()

    # ---- stats -------------------------------------------------------------

    def stats(self) -> dict:
        """The ``_nodes/stats.watchdog`` document: live in-flight waits
        (with the oldest wait's age — the liveness gauge OpenMetrics
        exports), the escalation tallies, and the envelope config."""
        from elasticsearch_tpu.search import jit_exec
        now = time.perf_counter()
        with self._lock:
            ages = [now - e.started for e in self._entries
                    if not e.done and not e.stalled]
            return {
                "enabled": self.enabled,
                "in_flight_waits": len(ages),
                "oldest_wait_age_seconds":
                    round(max(ages), 3) if ages else 0.0,
                "stalls": self.stalls,
                "abandoned": self.abandoned,
                "consecutive_stalls": self._consecutive_stalls,
                "quarantines": self.quarantines,
                "quarantined": jit_exec.plane_breaker.quarantined,
                "probes_attempted": self.probes_attempted,
                "probe_reopens": self.probe_reopens,
                "stall_multiplier": self.stall_multiplier,
                "floor_seconds": self.floor_s,
                "cold_floor_seconds": self.cold_floor_s,
                "ceiling_seconds": self.ceiling_s,
                "quarantine_stalls": self.quarantine_stalls,
            }

    def reset(self) -> None:
        """Drop all registered waits and tallies (tests)."""
        with self._lock:
            self._entries = []
            self._consecutive_stalls = 0
            self._probe_outcome = []
            self._next_probe_at = 0.0
            self.stalls = 0
            self.abandoned = 0
            self.quarantines = 0
            self.probe_reopens = 0
            self.probes_attempted = 0


#: THE per-node dispatch watchdog (module singleton — one process =
#: one device = one plane breaker = one watchdog; see module docstring)
dispatch_watchdog = DispatchWatchdog()


def settings_for(get) -> dict:
    """``configure()`` kwargs from node settings (``get`` is
    ``settings.get``-shaped): ``search.watchdog.{enabled,multiplier,
    floor_ms,cold_floor_ms,ceiling_ms,quarantine_stalls,
    probe_interval_ms,probe_budget_ms}``."""
    def _flag(key, default):
        val = get(key)
        return default if val is None \
            else str(val).lower() not in ("false", "0")
    out: dict = {"enabled": _flag("search.watchdog.enabled", True)}
    mult = get("search.watchdog.multiplier")
    if mult is not None:
        out["stall_multiplier"] = float(mult)
    for key, kwarg in (("search.watchdog.floor_ms", "floor_s"),
                       ("search.watchdog.cold_floor_ms", "cold_floor_s"),
                       ("search.watchdog.ceiling_ms", "ceiling_s"),
                       ("search.watchdog.probe_interval_ms",
                        "probe_interval_s"),
                       ("search.watchdog.probe_budget_ms",
                        "probe_budget_s")):
        val = get(key)
        if val is not None:
            out[kwarg] = float(val) / 1e3
    stalls = get("search.watchdog.quarantine_stalls")
    if stalls is not None:
        out["quarantine_stalls"] = int(stalls)
    return out
