"""Admission-queue micro-batching for request-at-a-time traffic.

The reference executes each search on its own thread the moment it
arrives (core/search/query/QueryPhase.java:314's per-request model over
the `search` thread pool). On an accelerator the economics invert: one
fused batched program amortizes the dispatch + device→host round trip
over every query in the batch (`ShardSearcher.query_phase_batch`), so the
winning server shape for concurrent low-rate clients is an admission
queue that coalesces whatever requests arrive within a tiny deadline into
one device batch — the same latency/throughput trade TPU serving stacks
make for model inference.

Semantics: each caller blocks until its own result is ready; a request
never waits longer than `max_wait_s` for peers, and a full batch
dispatches immediately. Ineligible requests (aggs, sort-by-field, …)
fall through to the caller's serial path untouched, so this is purely an
optimization layer — results are produced by the same
`query_phase_batch` program the msearch path uses.
"""

from __future__ import annotations

import inspect
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutTimeout

#: ceiling on `execute()`'s blocking wait for its own batch result — a
#: wedged run/drain must surface as a serial fallback (None) with a
#: `stalled` tally, never a caller thread parked forever
EXECUTE_STALL_S = 60.0


def pow2_bucket(n: int, cap: int | None = None) -> int:
    """Smallest power of two >= n (>= 1), clamped to `cap` when given.

    The one bucketing rule every batched/jitted layer shares — admission
    batches here, jit_exec's vmap batch axis, and the mesh plane's k and
    batch buckets — so a jagged size distribution compiles O(log N)
    programs instead of one per distinct count."""
    b = 1 if n <= 1 else 1 << (n - 1).bit_length()
    if cap is not None and b > cap:
        return cap
    return b


class AdaptiveBatcher:
    """Deadline-bounded micro-batch admission queue in front of a
    `query_phase_batch`-shaped callable.

    `run_batch(reqs) -> list[results] | None` — None means the batch was
    ineligible; every waiter then receives None and the caller runs its
    serial fallback.

    Pipelined mode: pass `drain_batch` and `run_batch` becomes the
    LAUNCH phase (`query_phase_batch_launch`-shaped: async device
    dispatch, returns an opaque handle or None-for-ineligible) while
    `drain_batch(handle) -> list[results]` blocks for the device→host
    transfer on a worker thread. Launching batch N+1 no longer waits for
    batch N's results to cross the interconnect — on a high-RTT link
    that drain otherwise idles the device for its full round trip. Up to
    `max_in_flight` batches may be launched-but-undrained at once (a
    semaphore backpressures the admission queue beyond that)."""

    def __init__(self, run_batch, max_batch: int = 64,
                 max_wait_s: float = 0.002, pad_to_bucket: bool = True,
                 drain_batch=None, max_in_flight: int = 4):
        self._run_batch = run_batch
        self._drain_batch = drain_batch
        if drain_batch is not None:
            self._inflight = threading.BoundedSemaphore(max_in_flight)
            self._drain_pool = ThreadPoolExecutor(
                max_workers=max_in_flight,
                thread_name_prefix="batch-drain")
        else:
            self._inflight = None
            self._drain_pool = None
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        # Pad formed batches up to the next power of two so a jitted
        # run_batch compiles O(log B) programs instead of one per
        # distinct arrival count — jagged batch sizes are the norm under
        # a deadline trigger. Padding replicates the FIRST request as a
        # no-op row (results sliced off before delivery); run_batch
        # callables that take `n_real` get the real-row count so lane
        # stats never count pad rows (query_phase_batch_launch does).
        self.pad_to_bucket = pad_to_bucket
        try:
            self._pass_n_real = "n_real" in \
                inspect.signature(run_batch).parameters
        except (TypeError, ValueError):      # builtins / C callables
            self._pass_n_real = False
        self._lock = threading.Lock()
        self._queue: list[tuple[object, Future]] = []
        self._timer: threading.Timer | None = None
        self._closed = False
        # dispatch counters (read by callers for telemetry; written under
        # _lock — full-batch and deadline dispatches run on different
        # threads)
        self.batches = 0
        self.requests = 0
        # execute() waits that hit the stall ceiling and fell back serial
        self.stalled = 0

    def bucket_sizes(self) -> list[int]:
        """Every batch size _dispatch can hand to run_batch: powers of two
        below max_batch plus max_batch itself. Callers that pre-compile
        (warm) programs iterate exactly this set."""
        if not self.pad_to_bucket:
            return list(range(1, self.max_batch + 1))
        sizes, b = [], 1
        while b < self.max_batch:
            sizes.append(b)
            b <<= 1
        sizes.append(self.max_batch)
        return sizes

    def submit(self, req) -> Future:
        """Enqueue one request; the Future resolves to its result (or None
        when the formed batch turned out ineligible)."""
        fut: Future = Future()
        with self._lock:
            if self._closed:
                fut.set_result(None)
                return fut
            self._queue.append((req, fut))
            full = len(self._queue) >= self.max_batch
            if full:
                batch = self._drain_locked()
            elif self._timer is None:
                t = threading.Timer(self.max_wait_s, self._deadline_fire)
                t.daemon = True
                t.start()
                self._timer = t
                batch = None
            else:
                batch = None
        if full:
            self._dispatch(batch)
        return fut

    def execute(self, req):
        """Blocking convenience: submit and wait. → result | None.

        BOUNDED: when the batch wedges past ``EXECUTE_STALL_S`` (hung
        device dispatch or drain) the wait is abandoned and the caller
        gets None — the serial-fallback contract — with the stall
        tallied. The batch thread still owns its futures; a late result
        resolves a future nobody reads, which is harmless."""
        try:
            return self.submit(req).result(EXECUTE_STALL_S)
        except FutTimeout:
            with self._lock:
                self.stalled += 1
            return None

    def close(self) -> None:
        with self._lock:
            self._closed = True
            batch = self._drain_locked()
        for _, fut in batch:
            fut.set_result(None)
        if self._drain_pool is not None:
            # let in-flight drains finish so no waiter hangs forever
            self._drain_pool.shutdown(wait=True)

    # ---- internals ---------------------------------------------------------

    def _drain_locked(self) -> list:
        batch, self._queue = self._queue, []
        if self._timer is not None:
            # a full-batch drain must defuse the pending deadline timer, or
            # it fires into the NEXT forming batch and fragments it
            self._timer.cancel()
            self._timer = None
        return batch

    def _deadline_fire(self) -> None:
        with self._lock:
            batch = self._drain_locked()
        if batch:
            self._dispatch(batch)

    def _dispatch(self, batch: list) -> None:
        reqs = [r for r, _ in batch]
        n_real = len(reqs)
        if self.pad_to_bucket and len(reqs) < self.max_batch:
            # bucket sizes that can reach run_batch: powers of two below
            # max_batch, plus max_batch itself (full batches form at
            # exactly max_batch anyway) — O(log B) distinct compiles even
            # for a non-power-of-two max_batch. Pad rows replicate the
            # first request only: cycling every queued request re-ran
            # real work through the program a second time and (on the
            # impact/knn lanes) double-counted admission stats
            bucket = pow2_bucket(len(reqs), self.max_batch)
            reqs = reqs + [reqs[0]] * (bucket - len(reqs))

        def run(rs):
            if self._pass_n_real and len(rs) != n_real:
                return self._run_batch(rs, n_real=n_real)
            return self._run_batch(rs)
        if self._drain_batch is not None:
            # pipelined: launch here (async device dispatch, fast), drain
            # on a worker — the next batch forms and launches while this
            # one's results ride the link
            self._inflight.acquire()
            with self._lock:
                closed = self._closed
            if closed:
                # close() raced us while we blocked on the in-flight
                # semaphore: the pool may already be shut down — resolve
                # the waiters (None = serial fallback) instead of leaving
                # them hung on futures nobody will complete
                self._inflight.release()
                for _, fut in batch:
                    if not fut.done():
                        fut.set_result(None)
                return
            try:
                handle = run(reqs)
            except Exception as e:           # noqa: BLE001 — fan the error out
                self._inflight.release()
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)
                return
            if handle is None:
                self._inflight.release()
                for _, fut in batch:
                    fut.set_result(None)
                return
            try:
                self._drain_pool.submit(self._drain_and_deliver, handle,
                                        batch)
            except RuntimeError:
                # pool shut down between the closed check and submit —
                # drain inline so the launched handle and its waiters
                # still complete
                self._drain_and_deliver(handle, batch)
            return
        try:
            results = run(reqs)
        except Exception as e:               # noqa: BLE001 — fan the error out
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        self._deliver(batch, results)

    def _drain_and_deliver(self, handle, batch: list) -> None:
        try:
            results = self._drain_batch(handle)
        except Exception as e:               # noqa: BLE001 — fan the error out
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            return
        finally:
            self._inflight.release()
        self._deliver(batch, results)

    def _deliver(self, batch: list, results) -> None:
        if results is None:
            for _, fut in batch:
                fut.set_result(None)
            return
        for (_, fut), res in zip(batch, results):
            if not fut.done():
                fut.set_result(res)
        with self._lock:
            self.batches += 1
            self.requests += len(batch)
