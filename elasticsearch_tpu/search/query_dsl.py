"""Query DSL: ES query JSON → typed query AST.

The reference registers ~50 Parser+Builder pairs (core/index/query/, 115
files; entry IndexQueryParserService.java). Here each query type is a
dataclass node; :func:`parse_query` maps the JSON body onto the AST, and the
executor (execute.py) lowers the AST to device kernels per segment.

Supported (reference parser in parens): match_all, match_none, match
(MatchQueryParser), match_phrase (+slop), multi_match, term/terms
(TermQueryParser/TermsQueryParser), range (RangeQueryParser), exists, prefix,
wildcard, regexp, fuzzy, ids, bool (BoolQueryParser), constant_score,
function_score (FunctionScoreQueryParser: field_value_factor, weight,
random_score, script_score, gauss/exp/linear decay), script_score, knn
(no 2015 equivalent — dense-vector path, BASELINE config 4), geo_distance,
geo_bounding_box, simple_query_string/query_string (reduced grammar),
dis_max, boosting, common, template, has_child/has_parent, nested, type,
more_like_this, missing, the full span algebra (span_term/near/or/not/
first/containing/within/multi + field_masking_span — min-end interval
maps, ops/spans.py), geo_polygon, geo_distance_range, geohash_cell,
geo_shape (vertex-ring relations, ops/geoshape.py), indices, and the 2.x
compat wrappers (not, and, or, filtered, limit, wrapper).
"""

from __future__ import annotations

from dataclasses import dataclass
from dataclasses import field as dc_field
from typing import Any

from elasticsearch_tpu.common.errors import QueryParsingError


@dataclass
class Query:
    boost: float = 1.0


@dataclass
class MatchAllQuery(Query):
    pass


@dataclass
class MatchNoneQuery(Query):
    pass


@dataclass
class MatchQuery(Query):
    field: str = ""
    text: str = ""
    operator: str = "or"              # or | and
    minimum_should_match: int | str | None = None
    analyzer: str | None = None


@dataclass
class MatchPhraseQuery(Query):
    field: str = ""
    text: str = ""
    slop: int = 0
    analyzer: str | None = None


@dataclass
class MultiMatchQuery(Query):
    fields: list[str] = dc_field(default_factory=list)   # may carry ^boost
    text: str = ""
    type: str = "best_fields"         # best_fields | most_fields | phrase
    operator: str = "or"
    tie_breaker: float = 0.0


@dataclass
class TermQuery(Query):
    field: str = ""
    value: Any = None


@dataclass
class TermsQuery(Query):
    field: str = ""
    values: list = dc_field(default_factory=list)


@dataclass
class RangeQuery(Query):
    field: str = ""
    gte: Any = None
    gt: Any = None
    lte: Any = None
    lt: Any = None


@dataclass
class ExistsQuery(Query):
    field: str = ""


@dataclass
class PrefixQuery(Query):
    field: str = ""
    value: str = ""


@dataclass
class WildcardQuery(Query):
    field: str = ""
    pattern: str = ""


@dataclass
class RegexpQuery(Query):
    field: str = ""
    pattern: str = ""


@dataclass
class FuzzyQuery(Query):
    field: str = ""
    value: str = ""
    fuzziness: int | str = "AUTO"


@dataclass
class IdsQuery(Query):
    values: list[str] = dc_field(default_factory=list)


@dataclass
class BoolQuery(Query):
    must: list[Query] = dc_field(default_factory=list)
    should: list[Query] = dc_field(default_factory=list)
    must_not: list[Query] = dc_field(default_factory=list)
    filter: list[Query] = dc_field(default_factory=list)
    minimum_should_match: int | str | None = None


@dataclass
class ConstantScoreQuery(Query):
    filter_query: Query | None = None


@dataclass
class DisMaxQuery(Query):
    """ref: core/index/query/DisMaxQueryParser.java — score = best
    sub-query + tie_breaker × the rest."""
    queries: list[Query] = dc_field(default_factory=list)
    tie_breaker: float = 0.0


@dataclass
class BoostingQuery(Query):
    """ref: core/index/query/BoostingQueryParser.java — positive matches,
    demoted (× negative_boost) when the negative query also matches."""
    positive: Query | None = None
    negative: Query | None = None
    negative_boost: float = 0.5


@dataclass
class CommonTermsQuery(Query):
    """ref: core/index/query/CommonTermsQueryParser.java — terms split by
    document frequency: low-freq terms gate the match, high-freq terms
    only contribute score."""
    field: str = ""
    text: str = ""
    cutoff_frequency: float = 0.01     # ≥1 → absolute df threshold
    low_freq_operator: str = "or"
    high_freq_operator: str = "or"
    minimum_should_match_low: int | str | None = None
    minimum_should_match_high: int | str | None = None
    analyzer: str | None = None


@dataclass
class SpanTermQuery(Query):
    """ref: core/index/query/SpanTermQueryParser.java."""
    field: str = ""
    value: str = ""


@dataclass
class SpanNearQuery(Query):
    """ref: core/index/query/SpanNearQueryParser.java — clauses must
    target one field; matches spans of width ≤ clauses+slop."""
    clauses: list[Query] = dc_field(default_factory=list)
    slop: int = 0
    in_order: bool = True


@dataclass
class SpanOrQuery(Query):
    """ref: core/index/query/SpanOrQueryParser.java — union of clause
    span sets."""
    clauses: list[Query] = dc_field(default_factory=list)


@dataclass
class SpanNotQuery(Query):
    """ref: core/index/query/SpanNotQueryParser.java — include spans not
    overlapping any exclude span (pre/post widen the kill window)."""
    include: Query | None = None
    exclude: Query | None = None
    pre: int = 0
    post: int = 0


@dataclass
class SpanFirstQuery(Query):
    """ref: core/index/query/SpanFirstQueryParser.java — match spans
    ending at position ≤ ``end``."""
    match: Query | None = None
    end: int = 0


@dataclass
class SpanContainingQuery(Query):
    """ref: core/index/query/SpanContainingQueryParser.java — spans of
    ``big`` that contain a ``little`` span."""
    big: Query | None = None
    little: Query | None = None


@dataclass
class SpanWithinQuery(Query):
    """ref: core/index/query/SpanWithinQueryParser.java — spans of
    ``little`` that lie inside a ``big`` span."""
    big: Query | None = None
    little: Query | None = None


@dataclass
class SpanMultiQuery(Query):
    """ref: core/index/query/SpanMultiTermQueryParser.java — a multi-term
    query (prefix/wildcard/regexp/fuzzy) as a span: expands against the
    segment term dictionary into a position-set leaf."""
    match: Query | None = None


@dataclass
class FieldMaskingSpanQuery(Query):
    """ref: core/index/query/FieldMaskingSpanQueryParser.java — report the
    inner span under another field name so cross-field span composition
    is allowed (positions evaluated on the INNER field's token matrix)."""
    query: Query | None = None
    field: str = ""


@dataclass
class HasChildQuery(Query):
    """ref: core/index/query/HasChildQueryParser.java — parents whose
    children (docs of `type`, joined via the _parent metadata column)
    match the inner query."""
    type: str = ""
    query: Query | None = None
    score_mode: str = "none"       # none|min|max|sum|avg
    min_children: int = 0
    max_children: int = 0          # 0 = unbounded


@dataclass
class HasParentQuery(Query):
    """ref: core/index/query/HasParentQueryParser.java — children whose
    parent doc (of `parent_type`) matches the inner query."""
    parent_type: str = ""
    query: Query | None = None
    score_mode: str = "none"       # none|score


@dataclass
class ParentIdsQuery(Query):
    """INTERNAL: the shard-local rewrite target of has_child/has_parent —
    match docs whose `field` value (_id or _parent) is a key of
    `id_scores`, scoring each doc with its mapped value (the host-side
    join result; cf. the reference's ParentIdsQuery)."""
    field: str = "_id"
    id_scores: dict = dc_field(default_factory=dict)


@dataclass
class NestedQuery(Query):
    """ref: core/index/query/NestedQueryParser.java — the inner query runs
    over a path's nested objects; a parent matches when any of its objects
    does, scored per score_mode."""
    path: str = ""
    query: Query | None = None
    score_mode: str = "avg"            # avg | sum | max | min | none


@dataclass
class MoreLikeThisQuery(Query):
    """ref: core/index/query/MoreLikeThisQueryParser.java — select the
    like-input's most significant terms (tf·idf) and match on them."""
    fields: list[str] = dc_field(default_factory=list)
    like_texts: list[str] = dc_field(default_factory=list)
    like_docs: list[dict] = dc_field(default_factory=list)  # {"_id": ...}
    # `unlike` inputs: their terms are REMOVED from the selected set
    unlike_texts: list[str] = dc_field(default_factory=list)
    unlike_docs: list[dict] = dc_field(default_factory=list)
    max_query_terms: int = 25
    min_term_freq: int = 2
    min_doc_freq: int = 5
    minimum_should_match: int | str | None = "30%"
    include: bool = False              # include the liked docs themselves
    # ids to exclude from results even when their text arrived pre-fetched
    # (the coordinator rewrites like-docs into like-texts + _exclude_ids —
    # search_action.rewrite_mlt_likes; the reference fetches liked docs at
    # the coordinator too, MoreLikeThisQueryParser + TransportMltAction)
    exclude_ids: list[str] = dc_field(default_factory=list)


@dataclass
class ScoreFunction:
    kind: str                          # field_value_factor | weight | random_score
    #                                  # | script_score | gauss | exp | linear
    params: dict = dc_field(default_factory=dict)
    filter_query: Query | None = None
    weight: float | None = None


@dataclass
class FunctionScoreQuery(Query):
    query: Query | None = None
    functions: list[ScoreFunction] = dc_field(default_factory=list)
    score_mode: str = "multiply"
    boost_mode: str = "multiply"
    max_boost: float | None = None
    min_score: float | None = None


@dataclass
class ScriptScoreQuery(Query):
    query: Query | None = None
    script: str = ""
    params: dict = dc_field(default_factory=dict)


@dataclass
class KnnQuery(Query):
    """Query-DSL leaf form (back-compat alias of the top-level ``knn``
    search section): scores every vector-carrying doc by cosine through
    the generic compiled path. New callers should use the top-level
    section (:class:`KnnSection`), which rides the dedicated knn lane
    with candidate oversampling, filters and hybrid fusion."""
    field: str = ""
    query_vector: list[float] = dc_field(default_factory=list)
    num_candidates: int | None = None


#: num_candidates ceiling (the ES bound) — a request past it is a 400
MAX_NUM_CANDIDATES = 10_000


@dataclass
class KnnSection:
    """The TOP-LEVEL ``"knn"`` search section (field, query_vector, k,
    num_candidates, filter, boost), combinable with a ``"query"`` clause
    for hybrid BM25+vector fusion. ``query_vector`` is a flat [D] list
    for ``dense_vector`` fields or a [T, D] list-of-lists for
    ``rank_vectors`` (late-interaction MaxSim). Search is EXACT
    (brute-force scoring of every live vector): ``num_candidates`` is
    the per-shard candidate depth each lane feeds into filtering and
    hybrid fusion — unlike ANN engines it never trades recall, it only
    bounds the fusion/merge width."""
    field: str = ""
    query_vector: list = dc_field(default_factory=list)
    k: int = 10
    num_candidates: int = 100
    filter: Query | None = None
    boost: float = 1.0
    multi: bool = False        # [T, D] late-interaction query
    hybrid: bool = False       # request also carries a "query" clause


def parse_knn_section(body) -> KnnSection:
    """Parse + validate the top-level ``knn`` section. Violations raise
    :class:`QueryParsingError` (the 400 the REST layer maps) at parse
    time — before any device work."""
    if not isinstance(body, dict):
        raise QueryParsingError("[knn] must be an object")
    field = body.get("field")
    if not field:
        raise QueryParsingError("[knn] requires [field]")
    qv = body.get("query_vector")
    if not isinstance(qv, list) or not qv:
        raise QueryParsingError(
            "[knn] requires a non-empty [query_vector]")
    multi = isinstance(qv[0], (list, tuple))
    if multi:
        dims = len(qv[0])
        for row in qv:
            if not isinstance(row, (list, tuple)) or len(row) != dims \
                    or not row:
                raise QueryParsingError(
                    "[knn] multi-vector query_vector rows must all "
                    "share one dimension")
        qv = [[float(x) for x in row] for row in qv]
    else:
        qv = [float(x) for x in qv]
    try:
        k = int(body.get("k", 10))
    except (TypeError, ValueError):
        raise QueryParsingError(
            f"[knn] k must be an integer, got [{body.get('k')}]") \
            from None
    if k < 1:
        raise QueryParsingError(f"[knn] k must be >= 1, got {k}")
    raw_nc = body.get("num_candidates", max(k, 100))
    try:
        nc = int(raw_nc)
    except (TypeError, ValueError):
        raise QueryParsingError(
            f"[knn] num_candidates must be an integer, got [{raw_nc}]") \
            from None
    if nc < k:
        raise QueryParsingError(
            f"[knn] num_candidates [{nc}] must be >= k [{k}]")
    if nc > MAX_NUM_CANDIDATES:
        raise QueryParsingError(
            f"[knn] num_candidates [{nc}] must be <= "
            f"{MAX_NUM_CANDIDATES}")
    boost = float(body.get("boost", 1.0))
    if boost <= 0:
        raise QueryParsingError(
            f"[knn] boost must be > 0, got {boost}")
    filt = None
    if body.get("filter") is not None:
        raw_f = body["filter"]
        if isinstance(raw_f, list):     # ES accepts a list of filters
            filt = BoolQuery(filter=[parse_query(f) for f in raw_f])
        else:
            filt = parse_query(raw_f)
    unknown = set(body) - {"field", "query_vector", "k",
                           "num_candidates", "filter", "boost"}
    if unknown:
        raise QueryParsingError(
            f"[knn] unknown parameter(s) {sorted(unknown)}")
    return KnnSection(field=str(field), query_vector=qv, k=k,
                      num_candidates=nc, filter=filt, boost=boost,
                      multi=multi)


@dataclass
class GeoDistanceQuery(Query):
    field: str = ""
    lat: float = 0.0
    lon: float = 0.0
    distance_m: float = 0.0


@dataclass
class GeoBoundingBoxQuery(Query):
    field: str = ""
    top: float = 0.0
    left: float = 0.0
    bottom: float = 0.0
    right: float = 0.0


@dataclass
class GeoPolygonQuery(Query):
    """ref: core/index/query/GeoPolygonQueryParser.java — point-in-polygon
    via even-odd ray casting over the vertex ring."""
    field: str = ""
    lats: list[float] = dc_field(default_factory=list)
    lons: list[float] = dc_field(default_factory=list)


@dataclass
class GeoDistanceRangeQuery(Query):
    """ref: core/index/query/GeoDistanceRangeQueryParser.java — annulus:
    from ≤ distance(point, origin) ≤ to."""
    field: str = ""
    lat: float = 0.0
    lon: float = 0.0
    gte_m: float | None = None
    gt_m: float | None = None
    lte_m: float | None = None
    lt_m: float | None = None


@dataclass
class GeohashCellQuery(Query):
    """ref: core/index/query/GeohashCellQuery.java — docs whose point
    falls in a geohash cell (plus the 8 neighbors when asked)."""
    field: str = ""
    geohash: str = ""
    neighbors: bool = False


@dataclass
class GeoShapeQuery(Query):
    """ref: core/index/query/GeoShapeQueryParser.java — spatial relation
    between each doc's indexed shape and the query shape."""
    field: str = ""
    shape: dict = dc_field(default_factory=dict)   # GeoJSON-ish body
    relation: str = "intersects"   # intersects | disjoint | within | contains


@dataclass
class IndicesQuery(Query):
    """ref: core/index/query/IndicesQueryParser.java — per-shard: run
    ``query`` when the shard's index is listed, else ``no_match_query``."""
    indices: list[str] = dc_field(default_factory=list)
    query: Query | None = None
    no_match_query: Query | None = None   # None = match_all (the default)


# ---------------------------------------------------------------------------
# parsing
# ---------------------------------------------------------------------------

_DISTANCE_UNITS = {"m": 1.0, "km": 1000.0, "mi": 1609.344, "yd": 0.9144,
                   "ft": 0.3048, "cm": 0.01, "mm": 0.001, "nmi": 1852.0}


def parse_distance(v: Any) -> float:
    if isinstance(v, (int, float)):
        return float(v)
    s = str(v).strip().lower()
    for unit in sorted(_DISTANCE_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            return float(s[: -len(unit)]) * _DISTANCE_UNITS[unit]
    return float(s)


def _field_body(body: dict, qtype: str) -> tuple[str, Any]:
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError(f"[{qtype}] query expects a single field")
    return next(iter(body.items()))


def _parse_msm(v) -> int | str | None:
    return v


def span_effective_fields(node: Query | None) -> set[str]:
    """The field(s) a span query's positions come from, AFTER masking:
    field_masking_span reports its mask field (that is its purpose —
    FieldMaskingSpanQueryParser), so validation that all clauses agree on
    one field treats masked clauses as the masked name."""
    if node is None:
        return set()
    t = type(node).__name__
    if t == "SpanTermQuery":
        return {node.field}
    if t == "FieldMaskingSpanQuery":
        return {node.field}
    if t == "SpanMultiQuery":
        f = getattr(node.match, "field", None)
        return {f} if f else set()
    if t in ("SpanOrQuery", "SpanNearQuery"):
        out: set[str] = set()
        for c in node.clauses:
            out |= span_effective_fields(c)
        return out
    if t == "SpanNotQuery":
        return span_effective_fields(node.include) | \
            span_effective_fields(node.exclude)
    if t == "SpanFirstQuery":
        return span_effective_fields(node.match)
    if t in ("SpanContainingQuery", "SpanWithinQuery"):
        return span_effective_fields(node.big) | \
            span_effective_fields(node.little)
    return set()


# Plugin-registered query parsers ({name: fn(body) -> Query}) — the SPI seam
# the reference exposes via IndicesQueriesModule/onModule(IndicesQueriesModule)
# (query parsers registered by plugins). PluginsService.apply_node_start fills
# this; parse_query falls back to it after the built-in arms.
EXTRA_PARSERS: dict[str, Any] = {}


def parse_query(body: dict | None) -> Query:  # noqa: C901 — one arm per query type
    if body is None or body == {}:
        return MatchAllQuery()
    if not isinstance(body, dict) or len(body) != 1:
        raise QueryParsingError(
            f"query must contain exactly one top-level type, got {list(body or {})}")
    qtype, qbody = next(iter(body.items()))

    if qtype == "match_all":
        return MatchAllQuery(boost=float(qbody.get("boost", 1.0)))
    if qtype == "match_none":
        return MatchNoneQuery()

    if qtype == "match":
        fname, spec = _field_body(qbody, "match")
        if isinstance(spec, dict):
            return MatchQuery(
                field=fname, text=str(spec.get("query", "")),
                operator=str(spec.get("operator", "or")).lower(),
                minimum_should_match=_parse_msm(spec.get("minimum_should_match")),
                analyzer=spec.get("analyzer"),
                boost=float(spec.get("boost", 1.0)))
        return MatchQuery(field=fname, text=str(spec))

    if qtype in ("match_phrase", "text_phrase"):
        fname, spec = _field_body(qbody, qtype)
        if isinstance(spec, dict):
            return MatchPhraseQuery(field=fname, text=str(spec.get("query", "")),
                                    slop=int(spec.get("slop", 0)),
                                    analyzer=spec.get("analyzer"),
                                    boost=float(spec.get("boost", 1.0)))
        return MatchPhraseQuery(field=fname, text=str(spec))

    if qtype == "multi_match":
        return MultiMatchQuery(
            fields=list(qbody.get("fields", [])), text=str(qbody.get("query", "")),
            type=qbody.get("type", "best_fields"),
            operator=str(qbody.get("operator", "or")).lower(),
            tie_breaker=float(qbody.get("tie_breaker", 0.0)),
            boost=float(qbody.get("boost", 1.0)))

    if qtype in ("term", "terms") and isinstance(qbody, dict) \
            and len(qbody) == 1 and next(iter(qbody)) in ("_id", "_uid"):
        # the _id/_uid metadata field resolves through the ids query
        # (ref: core/index/mapper/internal/IdFieldMapper termQuery)
        _f, spec = next(iter(qbody.items()))
        vals = spec.get("value", spec.get("values")) \
            if isinstance(spec, dict) else spec
        vals = vals if isinstance(vals, list) else [vals]
        return IdsQuery(values=[str(v) for v in vals])

    if qtype == "term":
        fname, spec = _field_body(qbody, "term")
        if isinstance(spec, dict):
            return TermQuery(field=fname, value=spec.get("value"),
                             boost=float(spec.get("boost", 1.0)))
        return TermQuery(field=fname, value=spec)

    if qtype == "terms":
        items = {k: v for k, v in qbody.items() if k != "boost"}
        fname, values = _field_body(items, "terms")
        return TermsQuery(field=fname, values=list(values),
                          boost=float(qbody.get("boost", 1.0)))

    if qtype == "range":
        fname, spec = _field_body(qbody, "range")
        if not isinstance(spec, dict):
            raise QueryParsingError("[range] expects an object of bounds")
        # gt/gte (and lt/lte) share ONE bound slot, last key in body
        # order wins — the reference's RangeQueryParser assigns from/
        # includeLower per parsed key IN BODY ORDER, so a later gt
        # overwrites an earlier gte entirely and include_lower/
        # include_upper (the 2.x flag spellings) also apply at their
        # position ("from" leaves the inclusivity flag untouched)
        lo = hi = None
        lo_incl = hi_incl = True
        for kk, vv in spec.items():
            if kk == "from":
                lo = vv
            elif kk == "gte":
                lo, lo_incl = vv, True
            elif kk == "gt":
                lo, lo_incl = vv, False
            elif kk == "include_lower":
                lo_incl = bool(vv)
            elif kk == "to":
                hi = vv
            elif kk == "lte":
                hi, hi_incl = vv, True
            elif kk == "lt":
                hi, hi_incl = vv, False
            elif kk == "include_upper":
                hi_incl = bool(vv)
        return RangeQuery(field=fname,
                          gte=lo if lo_incl else None,
                          gt=None if lo_incl else lo,
                          lte=hi if hi_incl else None,
                          lt=None if hi_incl else hi,
                          boost=float(spec.get("boost", 1.0)))

    if qtype == "exists":
        return ExistsQuery(field=qbody["field"])
    if qtype == "missing":  # ES 2.x: missing == must_not exists
        return BoolQuery(must_not=[ExistsQuery(field=qbody["field"])])

    if qtype == "prefix":
        fname, spec = _field_body(qbody, "prefix")
        if isinstance(spec, dict):
            return PrefixQuery(field=fname, value=str(spec.get("value", "")),
                               boost=float(spec.get("boost", 1.0)))
        return PrefixQuery(field=fname, value=str(spec))

    if qtype == "wildcard":
        fname, spec = _field_body(qbody, "wildcard")
        if isinstance(spec, dict):
            return WildcardQuery(field=fname,
                                 pattern=str(spec.get("value", spec.get("wildcard", ""))),
                                 boost=float(spec.get("boost", 1.0)))
        return WildcardQuery(field=fname, pattern=str(spec))

    if qtype == "regexp":
        fname, spec = _field_body(qbody, "regexp")
        if isinstance(spec, dict):
            return RegexpQuery(field=fname, pattern=str(spec.get("value", "")),
                               boost=float(spec.get("boost", 1.0)))
        return RegexpQuery(field=fname, pattern=str(spec))

    if qtype == "fuzzy":
        fname, spec = _field_body(qbody, "fuzzy")
        if isinstance(spec, dict):
            return FuzzyQuery(field=fname, value=str(spec.get("value", "")),
                              fuzziness=spec.get("fuzziness", "AUTO"),
                              boost=float(spec.get("boost", 1.0)))
        return FuzzyQuery(field=fname, value=str(spec))

    if qtype == "ids":
        return IdsQuery(values=[str(v) for v in qbody.get("values", [])])

    if qtype == "bool":
        def as_list(v):
            if v is None:
                return []
            return v if isinstance(v, list) else [v]
        return BoolQuery(
            must=[parse_query(q) for q in as_list(qbody.get("must"))],
            should=[parse_query(q) for q in as_list(qbody.get("should"))],
            must_not=[parse_query(q) for q in as_list(qbody.get("must_not"))],
            filter=[parse_query(q) for q in as_list(qbody.get("filter"))],
            minimum_should_match=_parse_msm(qbody.get("minimum_should_match")),
            boost=float(qbody.get("boost", 1.0)))

    if qtype == "constant_score":
        return ConstantScoreQuery(
            filter_query=parse_query(qbody.get("filter", qbody.get("query"))),
            boost=float(qbody.get("boost", 1.0)))

    if qtype == "dis_max":
        return DisMaxQuery(
            queries=[parse_query(sub) for sub in qbody.get("queries", [])],
            tie_breaker=float(qbody.get("tie_breaker", 0.0)),
            boost=float(qbody.get("boost", 1.0)))

    if qtype == "boosting":
        if "positive" not in qbody or "negative" not in qbody:
            raise QueryParsingError(
                "[boosting] query requires 'positive' and 'negative'")
        return BoostingQuery(
            positive=parse_query(qbody["positive"]),
            negative=parse_query(qbody["negative"]),
            negative_boost=float(qbody.get("negative_boost", 0.5)),
            boost=float(qbody.get("boost", 1.0)))

    if qtype == "common":
        fname, spec = _field_body(qbody, "common")
        if not isinstance(spec, dict):
            spec = {"query": spec}
        msm = spec.get("minimum_should_match")
        msm_low = msm_high = None
        if isinstance(msm, dict):
            msm_low = _parse_msm(msm.get("low_freq"))
            msm_high = _parse_msm(msm.get("high_freq"))
        else:
            msm_low = _parse_msm(msm)
        return CommonTermsQuery(
            field=fname, text=str(spec.get("query", "")),
            cutoff_frequency=float(spec.get("cutoff_frequency", 0.01)),
            low_freq_operator=str(spec.get("low_freq_operator",
                                           "or")).lower(),
            high_freq_operator=str(spec.get("high_freq_operator",
                                            "or")).lower(),
            minimum_should_match_low=msm_low,
            minimum_should_match_high=msm_high,
            analyzer=spec.get("analyzer"),
            boost=float(spec.get("boost", 1.0)))

    if qtype == "span_term":
        fname, spec = _field_body(qbody, "span_term")
        if isinstance(spec, dict):
            return SpanTermQuery(field=fname,
                                 value=str(spec.get("value",
                                                    spec.get("term", ""))),
                                 boost=float(spec.get("boost", 1.0)))
        return SpanTermQuery(field=fname, value=str(spec))

    if qtype == "span_near":
        clauses = [parse_query(c) for c in qbody.get("clauses", [])]
        if not clauses:
            raise QueryParsingError("[span_near] requires clauses")
        span_types = (SpanTermQuery, SpanNearQuery, SpanOrQuery,
                      SpanNotQuery, SpanFirstQuery, SpanContainingQuery,
                      SpanWithinQuery, SpanMultiQuery,
                      FieldMaskingSpanQuery)
        for c in clauses:
            if not isinstance(c, span_types):
                raise QueryParsingError(
                    "[span_near] clauses must be span queries")
        fields = set()
        for c in clauses:
            fields |= span_effective_fields(c)
        if len(fields) > 1:
            raise QueryParsingError(
                "[span_near] clauses must target one field "
                "(use field_masking_span to combine fields)")
        return SpanNearQuery(clauses=clauses,
                             slop=int(qbody.get("slop", 0)),
                             in_order=bool(qbody.get("in_order", True)),
                             boost=float(qbody.get("boost", 1.0)))

    if qtype == "template":
        # template QUERY (ref: core/index/query/TemplateQueryParser.java):
        # render the mustache body to a query dict, then parse it
        from elasticsearch_tpu.search.templates import render_search_template
        spec = dict(qbody)
        if "query" in spec and "inline" not in spec and "source" not in spec:
            spec["template"] = spec.pop("query")
        rendered = render_search_template(spec, lambda _i: None)
        return parse_query(rendered)

    if qtype == "has_child":
        if "type" not in qbody or "query" not in qbody:
            raise QueryParsingError("[has_child] requires 'type' and "
                                    "'query'")
        sm = str(qbody.get("score_mode", "none")).lower()
        if sm == "total":                  # 2.x alias
            sm = "sum"
        return HasChildQuery(type=str(qbody["type"]),
                             query=parse_query(qbody["query"]),
                             score_mode=sm,
                             min_children=int(qbody.get("min_children", 0)),
                             max_children=int(qbody.get("max_children", 0)),
                             boost=float(qbody.get("boost", 1.0)))

    if qtype == "has_parent":
        ptype = qbody.get("parent_type", qbody.get("type"))
        if ptype is None or "query" not in qbody:
            raise QueryParsingError("[has_parent] requires 'parent_type' "
                                    "and 'query'")
        sm = str(qbody.get("score_mode", "none")).lower()
        return HasParentQuery(parent_type=str(ptype),
                              query=parse_query(qbody["query"]),
                              score_mode=sm,
                              boost=float(qbody.get("boost", 1.0)))

    if qtype == "type":
        # {"type": {"value": t}} filters by the _type metadata column
        # (ref: TypeQueryParser)
        return TermQuery(field="_type", value=str(qbody.get("value", "")))

    if qtype == "nested":
        if "path" not in qbody or "query" not in qbody:
            raise QueryParsingError("[nested] requires 'path' and 'query'")
        score_mode = str(qbody.get("score_mode", "avg")).lower()
        if score_mode == "total":          # 2.x alias
            score_mode = "sum"
        if score_mode not in ("avg", "sum", "max", "min", "none"):
            raise QueryParsingError(
                f"illegal score_mode for nested query [{score_mode}]")
        return NestedQuery(path=str(qbody["path"]),
                           query=parse_query(qbody["query"]),
                           score_mode=score_mode,
                           boost=float(qbody.get("boost", 1.0)))

    if qtype in ("more_like_this", "mlt"):
        like_texts: list[str] = []
        like_docs: list[dict] = []
        raw_like = qbody.get("like", qbody.get("like_text"))
        for item in (raw_like if isinstance(raw_like, list)
                     else [raw_like] if raw_like is not None else []):
            if isinstance(item, dict):
                like_docs.append(item)
            else:
                like_texts.append(str(item))
        for did in qbody.get("ids", []) or []:
            like_docs.append(did if isinstance(did, dict) else {"_id": did})
        for item in qbody.get("docs", []) or []:
            if isinstance(item, dict) and "doc" in item:
                # artificial document: its string values are like-texts
                like_texts.extend(str(v) for v in item["doc"].values()
                                  if isinstance(v, str))
            else:
                like_docs.append(item if isinstance(item, dict)
                                 else {"_id": item})
        unlike_texts: list[str] = []
        unlike_docs: list[dict] = []
        raw_unlike = qbody.get("unlike")
        for item in (raw_unlike if isinstance(raw_unlike, list)
                     else [raw_unlike] if raw_unlike is not None else []):
            if isinstance(item, dict) and "doc" in item:
                unlike_texts.extend(str(v) for v in item["doc"].values()
                                    if isinstance(v, str))
            elif isinstance(item, dict):
                unlike_docs.append(item)
            else:
                unlike_texts.append(str(item))
        if not like_texts and not like_docs:
            raise QueryParsingError(
                "[more_like_this] requires 'like' text or docs")
        fields = qbody.get("fields", [])
        return MoreLikeThisQuery(
            fields=list(fields),
            like_texts=like_texts, like_docs=like_docs,
            unlike_texts=unlike_texts, unlike_docs=unlike_docs,
            exclude_ids=[str(x) for x in qbody.get("_exclude_ids", [])],
            max_query_terms=int(qbody.get("max_query_terms", 25)),
            min_term_freq=int(qbody.get("min_term_freq", 2)),
            min_doc_freq=int(qbody.get("min_doc_freq", 5)),
            minimum_should_match=_parse_msm(
                qbody.get("minimum_should_match", "30%")),
            include=bool(qbody.get("include", False)),
            boost=float(qbody.get("boost", 1.0)))

    if qtype == "function_score":
        functions = []
        raw_fns = qbody.get("functions")
        if raw_fns is None:
            raw_fns = [ {k: v for k, v in qbody.items()
                         if k in ("field_value_factor", "script_score", "weight",
                                  "random_score", "gauss", "exp", "linear")} ]
        for fdef in raw_fns:
            fq = parse_query(fdef["filter"]) if "filter" in fdef else None
            weight = fdef.get("weight")
            kind, params = None, {}
            for key in ("field_value_factor", "script_score", "random_score",
                        "gauss", "exp", "linear"):
                if key in fdef:
                    kind = key
                    params = fdef[key]
                    break
            if kind is None:
                if weight is None:
                    raise QueryParsingError("function_score function without type")
                kind = "weight"
            functions.append(ScoreFunction(kind=kind, params=params,
                                           filter_query=fq,
                                           weight=None if weight is None
                                           else float(weight)))
        return FunctionScoreQuery(
            query=parse_query(qbody.get("query")),
            functions=functions,
            score_mode=qbody.get("score_mode", "multiply"),
            boost_mode=qbody.get("boost_mode", "multiply"),
            max_boost=(None if qbody.get("max_boost") is None
                       else float(qbody["max_boost"])),
            min_score=(None if qbody.get("min_score") is None
                       else float(qbody["min_score"])),
            boost=float(qbody.get("boost", 1.0)))

    if qtype == "script_score":
        script = qbody.get("script", {})
        if isinstance(script, dict):
            src = script.get("source", script.get("inline", ""))
            params = script.get("params", {})
        else:
            src, params = str(script), {}
        return ScriptScoreQuery(query=parse_query(qbody.get("query")),
                                script=src, params=params,
                                boost=float(qbody.get("boost", 1.0)))

    if qtype == "knn":
        return KnnQuery(field=qbody["field"],
                        query_vector=list(qbody["query_vector"]),
                        num_candidates=qbody.get("num_candidates"),
                        boost=float(qbody.get("boost", 1.0)))

    if qtype == "geo_distance":
        dist = parse_distance(qbody.get("distance"))
        point_items = {k: v for k, v in qbody.items() if k != "distance"}
        fname, point = next(iter(point_items.items()))
        if isinstance(point, dict):
            lat, lon = float(point["lat"]), float(point["lon"])
        elif isinstance(point, (list, tuple)):
            lon, lat = float(point[0]), float(point[1])
        else:
            lat, lon = (float(x) for x in str(point).split(","))
        return GeoDistanceQuery(field=fname, lat=lat, lon=lon, distance_m=dist)

    if qtype == "geo_bounding_box":
        fname, box = next(iter(qbody.items()))
        tl, br = box["top_left"], box["bottom_right"]
        return GeoBoundingBoxQuery(field=fname,
                                   top=float(tl["lat"]), left=float(tl["lon"]),
                                   bottom=float(br["lat"]), right=float(br["lon"]))

    if qtype in ("query_string", "simple_query_string"):
        from elasticsearch_tpu.search.query_string import parse_query_string
        return parse_query_string(qbody)

    # ---- span algebra (SpanOr/Not/First/Containing/Within/MultiTerm,
    # FieldMaskingSpan parsers under core/index/query/) -------------------
    if qtype == "span_or":
        clauses = [parse_query(c) for c in qbody.get("clauses", [])]
        if not clauses:
            raise QueryParsingError("[span_or] requires 'clauses'")
        fields = set()
        for c in clauses:
            fields |= span_effective_fields(c)
        if len(fields) > 1:
            raise QueryParsingError(
                "[span_or] clauses must target one field "
                "(use field_masking_span to combine fields)")
        return SpanOrQuery(clauses=clauses,
                           boost=float(qbody.get("boost", 1.0)))
    if qtype == "span_not":
        if "include" not in qbody or "exclude" not in qbody:
            raise QueryParsingError(
                "[span_not] requires 'include' and 'exclude'")
        dist = int(qbody.get("dist", 0))
        return SpanNotQuery(include=parse_query(qbody["include"]),
                            exclude=parse_query(qbody["exclude"]),
                            pre=int(qbody.get("pre", dist)),
                            post=int(qbody.get("post", dist)),
                            boost=float(qbody.get("boost", 1.0)))
    if qtype == "span_first":
        if "match" not in qbody:
            raise QueryParsingError("[span_first] requires 'match'")
        return SpanFirstQuery(match=parse_query(qbody["match"]),
                              end=int(qbody.get("end", 0)),
                              boost=float(qbody.get("boost", 1.0)))
    if qtype in ("span_containing", "span_within"):
        if "big" not in qbody or "little" not in qbody:
            raise QueryParsingError(
                f"[{qtype}] requires 'big' and 'little'")
        cls = SpanContainingQuery if qtype == "span_containing" \
            else SpanWithinQuery
        return cls(big=parse_query(qbody["big"]),
                   little=parse_query(qbody["little"]),
                   boost=float(qbody.get("boost", 1.0)))
    if qtype == "span_multi":
        if "match" not in qbody:
            raise QueryParsingError("[span_multi] requires 'match'")
        return SpanMultiQuery(match=parse_query(qbody["match"]),
                              boost=float(qbody.get("boost", 1.0)))
    if qtype == "field_masking_span":
        if "query" not in qbody or "field" not in qbody:
            raise QueryParsingError(
                "[field_masking_span] requires 'query' and 'field'")
        return FieldMaskingSpanQuery(query=parse_query(qbody["query"]),
                                     field=str(qbody["field"]),
                                     boost=float(qbody.get("boost", 1.0)))

    # ---- geo long tail --------------------------------------------------
    if qtype == "geo_polygon":
        fname, spec = _field_body(qbody, "geo_polygon")
        lats, lons = [], []
        for p in spec.get("points", []):
            if isinstance(p, dict):
                lats.append(float(p["lat"]))
                lons.append(float(p["lon"]))
            elif isinstance(p, (list, tuple)):
                lons.append(float(p[0]))
                lats.append(float(p[1]))
            else:
                la, lo = (float(x) for x in str(p).split(","))
                lats.append(la)
                lons.append(lo)
        if len(lats) < 3:
            raise QueryParsingError(
                "[geo_polygon] requires at least 3 points")
        return GeoPolygonQuery(field=fname, lats=lats, lons=lons)
    if qtype == "geo_distance_range":
        keys = {"from", "to", "gte", "gt", "lte", "lt", "include_lower",
                "include_upper", "unit", "distance_type", "boost",
                "_name", "validation_method", "optimize_bbox"}
        point_items = {k: v for k, v in qbody.items()
                       if k not in keys and not k.startswith("_")}
        if not point_items:
            raise QueryParsingError(
                "[geo_distance_range] requires a geo_point field")
        fname, point = next(iter(point_items.items()))
        if isinstance(point, dict):
            lat, lon = float(point["lat"]), float(point["lon"])
        elif isinstance(point, (list, tuple)):
            lon, lat = float(point[0]), float(point[1])
        else:
            lat, lon = (float(x) for x in str(point).split(","))
        inc_lo = bool(qbody.get("include_lower", True))
        inc_hi = bool(qbody.get("include_upper", True))
        lo = qbody.get("gte", qbody.get("from"))
        lo_x = qbody.get("gt")
        hi = qbody.get("lte", qbody.get("to"))
        hi_x = qbody.get("lt")
        if lo is not None and not inc_lo:
            lo, lo_x = None, lo
        if hi is not None and not inc_hi:
            hi, hi_x = None, hi
        return GeoDistanceRangeQuery(
            field=fname, lat=lat, lon=lon,
            gte_m=None if lo is None else parse_distance(lo),
            gt_m=None if lo_x is None else parse_distance(lo_x),
            lte_m=None if hi is None else parse_distance(hi),
            lt_m=None if hi_x is None else parse_distance(hi_x))
    if qtype in ("geohash_cell", "geohash_filter"):
        from elasticsearch_tpu.utils.geohash import (
            geohash_encode, precision_to_length)
        cell_items = [(k, v) for k, v in qbody.items()
                      if k not in ("precision", "neighbors", "boost")
                      and not k.startswith("_")]
        if not cell_items:
            raise QueryParsingError(
                "[geohash_cell] requires a geo_point field")
        fname, spec = cell_items[0]
        length = precision_to_length(qbody["precision"]) \
            if "precision" in qbody else 12
        if isinstance(spec, dict) and "geohash" in spec:
            gh = str(spec["geohash"])[:length]
        elif isinstance(spec, dict) and "lat" in spec and "lon" in spec:
            gh = geohash_encode(float(spec["lat"]), float(spec["lon"]),
                                length)
        elif isinstance(spec, (list, tuple)):       # GeoJSON [lon, lat]
            gh = geohash_encode(float(spec[1]), float(spec[0]), length)
        elif isinstance(spec, dict):
            raise QueryParsingError(
                f"[geohash_cell] cannot parse point [{spec!r}]")
        else:
            gh = str(spec)[:length]
        return GeohashCellQuery(field=fname, geohash=gh,
                                neighbors=bool(qbody.get("neighbors",
                                                         False)))
    if qtype == "geo_shape":
        fname, spec = _field_body(qbody, "geo_shape")
        shape = spec.get("shape")
        if shape is None:
            raise QueryParsingError(
                "[geo_shape] requires an inline 'shape' "
                "(indexed-shape lookup is resolved by the caller)")
        return GeoShapeQuery(field=fname, shape=dict(shape),
                             relation=str(spec.get("relation",
                                                   "intersects")).lower())

    # ---- compatibility / wrapper types ----------------------------------
    if qtype == "indices":
        idx = qbody.get("indices", qbody.get("index"))
        if idx is None or "query" not in qbody:
            raise QueryParsingError(
                "[indices] requires 'indices' and 'query'")
        nmq = qbody.get("no_match_query", "all")
        if nmq == "all":
            no_match = None
        elif nmq == "none":
            no_match = MatchNoneQuery()
        else:
            no_match = parse_query(nmq)
        return IndicesQuery(
            indices=[idx] if isinstance(idx, str) else [str(i) for i in idx],
            query=parse_query(qbody["query"]), no_match_query=no_match)
    if qtype == "not":
        # ref: NotQueryParser — matches docs NOT matching the inner query
        # (accepts the bare, {"query": ...} and 1.x {"filter": ...} forms)
        inner = qbody
        if isinstance(qbody, dict):
            inner = qbody.get("query", qbody.get("filter", qbody))
        return BoolQuery(must=[MatchAllQuery()],
                         must_not=[parse_query(inner)])
    if qtype == "and":
        clauses = qbody.get("filters", qbody) if isinstance(qbody, dict) \
            else qbody
        return BoolQuery(filter=[parse_query(c) for c in clauses])
    if qtype == "or":
        clauses = qbody.get("filters", qbody) if isinstance(qbody, dict) \
            else qbody
        return BoolQuery(should=[parse_query(c) for c in clauses],
                         minimum_should_match=1)
    if qtype == "filtered":
        # 2.x compat (FilteredQueryParser): query scored, filter as mask
        out = BoolQuery(must=[parse_query(qbody.get("query"))])
        if qbody.get("filter") is not None:
            out.filter = [parse_query(qbody["filter"])]
        return out
    if qtype == "limit":
        # deprecated in 2.x: parses and matches everything (LimitQueryParser)
        return MatchAllQuery()
    if qtype == "wrapper":
        import base64
        import json as _json
        raw = qbody.get("query") if isinstance(qbody, dict) else qbody
        try:
            decoded = _json.loads(base64.b64decode(raw))
        except Exception as e:
            raise QueryParsingError(f"[wrapper] bad base64 query: {e}")
        return parse_query(decoded)

    extra = EXTRA_PARSERS.get(qtype)
    if extra is not None:
        return extra(qbody)

    raise QueryParsingError(f"unknown query type [{qtype}]")
