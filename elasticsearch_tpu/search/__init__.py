from elasticsearch_tpu.search.query_dsl import parse_query

__all__ = ["parse_query"]
