from elasticsearch_tpu.search.query_dsl import parse_query
from elasticsearch_tpu.search.service import SearchService

__all__ = ["parse_query", "SearchService"]
