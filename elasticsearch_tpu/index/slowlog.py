"""Threshold-based slow logs for search and indexing.

Reference: core/index/search/stats/SearchSlowLog.java and
core/index/indexing/IndexingSlowLog.java — per-index warn/info/debug/trace
time thresholds (`index.search.slowlog.threshold.query.warn`,
`index.indexing.slowlog.threshold.index.warn`, …) gating log lines on the
standard logging hierarchy, updated dynamically with index settings.
"""

from __future__ import annotations

import logging

from elasticsearch_tpu.common.settings import Settings, parse_time_value

_LEVELS = (("warn", logging.WARNING), ("info", logging.INFO),
           ("debug", logging.DEBUG), ("trace", 5))


class SlowLog:
    _prefix: str = ""

    def __init__(self, index_name: str, settings: Settings,
                 logger_name: str):
        self.index_name = index_name
        self.logger = logging.getLogger(logger_name)
        self.thresholds: list[tuple[float, int, str]] = []
        self.update_settings(settings)

    def update_settings(self, settings: Settings) -> None:
        self.thresholds = []
        for name, level in _LEVELS:
            raw = settings.get(f"{self._prefix}.{name}")
            if raw in (None, "", "-1"):
                continue
            try:
                self.thresholds.append(
                    (parse_time_value(str(raw), name), level, name))
            except (ValueError, TypeError):
                continue
        self.thresholds.sort(reverse=True)       # strictest (longest) first

    def maybe_log(self, took_s: float, message: str) -> str | None:
        """Log at the highest level whose threshold `took_s` exceeds;
        → the level name logged at (for tests), or None. Lines carry the
        executing task id and its parent/trace id (TaskManager wiring)
        so a slow shard query joins back to its coordinating request,
        plus the plane attribution of the request — admission path,
        fallback reason, program-cache hits/misses, and the device
        dispatch share of ``took`` — so a slow query is diagnosable
        from the log line alone."""
        for threshold, level, name in self.thresholds:
            if took_s >= threshold:
                from elasticsearch_tpu.observability import attribution
                from elasticsearch_tpu.tasks import current_task
                task = current_task()
                if task is not None:
                    message = (f"{message}, task[{task.task_id}], "
                               f"parent[{task.parent_task_id or '-'}]")
                extra = attribution.render_current(took_s)
                if extra:
                    message = f"{message}, {extra}"
                self.logger.log(
                    level, "[%s] took[%.1fms], %s",
                    self.index_name, took_s * 1000.0, message)
                return name
        return None


class SearchSlowLog(SlowLog):
    _prefix = "index.search.slowlog.threshold.query"

    def __init__(self, index_name: str, settings: Settings):
        super().__init__(index_name, settings, "index.search.slowlog")


class IndexingSlowLog(SlowLog):
    _prefix = "index.indexing.slowlog.threshold.index"

    def __init__(self, index_name: str, settings: Settings):
        super().__init__(index_name, settings, "index.indexing.slowlog")
