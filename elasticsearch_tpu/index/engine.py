"""Engine — per-shard versioned CRUD orchestration.

The TPU-native counterpart of the reference's InternalEngine
(core/index/engine/InternalEngine.java): it owns

* an in-memory write buffer (:class:`SegmentBuilder`) — Lucene IndexWriter's
  RAM buffer;
* the committed immutable segment list + per-segment live bitmaps;
* the **version map** (doc _id → version/location) backing realtime get and
  optimistic concurrency (LiveVersionMap, InternalEngine.java:97,359,408);
* the :class:`Translog` WAL (add on every op, InternalEngine.java:335→
  translog.add);
* ``refresh()`` — turn the buffer into a searchable segment and swap the
  reader (InternalEngine.java:558);
* ``flush()`` — persist segments + commit point, roll the translog
  (InternalEngine.java:616);
* recovery — reopen last commit and replay uncommitted translog ops
  (InternalEngine.java:215).

Deletes against committed segments flip bits in the per-segment live bitmap
at refresh time (Lucene .liv semantics: visible to search after refresh,
immediately visible to realtime get via the version map).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from elasticsearch_tpu.common.errors import (
    DocumentMissingError, EngineClosedError, VersionConflictError)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.segment import (
    Segment, SegmentBuilder, merge_segments, row_meta)
from elasticsearch_tpu.index.translog import (
    Translog, TranslogOp, OP_INDEX, OP_DELETE, DURABILITY_REQUEST)
from elasticsearch_tpu.mapping import MapperService

# Versioning ops match the reference's VersionType.INTERNAL semantics.
MATCH_ANY = -3  # Versions.MATCH_ANY
NOT_FOUND = -1


_VERSION_TYPES = ("internal", "external", "external_gt", "external_gte",
                  "force")


def _check_external_args(doc_id: str, version: int,
                         version_type: str) -> None:
    """VersionType validation (400-class): unknown types are rejected and
    non-internal types REQUIRE an explicit version (the reference's
    action_request_validation, not a 409)."""
    from elasticsearch_tpu.common.errors import IllegalArgumentError
    if version_type not in _VERSION_TYPES:
        raise IllegalArgumentError(
            f"version type [{version_type}] is not supported")
    if version == MATCH_ANY:
        raise IllegalArgumentError(
            f"[{doc_id}] version must be set when version_type is "
            f"[{version_type}]")


@dataclass
class VersionEntry:
    version: int
    deleted: bool
    seg_id: int      # -1 = in the uncommitted buffer
    local_doc: int   # position within segment/buffer


@dataclass
class GetResult:
    found: bool
    doc_id: str
    version: int = 0
    source: dict | None = None
    # metadata-field values (_type/_parent/_timestamp/_ttl) read back from
    # the doc's parsed fields or segment columns
    meta: dict | None = None


@dataclass
class EngineStats:
    index_total: int = 0
    delete_total: int = 0
    refresh_total: int = 0
    flush_total: int = 0
    merge_total: int = 0
    index_time_ms: float = 0.0


class SearcherView:
    """An immutable point-in-time view: segments + live masks.

    The analog of an NRT reader acquired via IndexShard.acquireSearcher
    (core/index/shard/IndexShard.java:707). DeviceReader (ops layer) packs
    this onto the device.
    """

    def __init__(self, segments: list[Segment], live_masks: list[np.ndarray],
                 generation: int):
        self.segments = segments
        self.live_masks = live_masks   # [padded_docs] bool per segment
        self.generation = generation

    @property
    def num_docs(self) -> int:
        return int(sum(m[:s.num_docs].sum() for s, m in
                       zip(self.segments, self.live_masks)))

    @property
    def max_doc(self) -> int:
        return sum(s.num_docs for s in self.segments)


def _parsed_meta(doc) -> dict | None:
    """Metadata-field values out of a buffered ParsedDocument."""
    out = {}
    for key in ("_type", "_parent", "_routing"):
        f = doc.fields.get(key)
        if f is not None and f.keywords:
            out[key] = f.keywords[0]
    for key in ("_timestamp", "_ttl"):
        f = doc.fields.get(key)
        if f is not None and f.numerics:
            out[key] = int(f.numerics[0])
    return out or None


def _segment_meta(seg, local: int) -> dict | None:
    """Metadata-field values out of a committed segment's columns."""
    return row_meta(seg, local) or None


class Engine:
    def __init__(self, shard_path: Path, mapper_service: MapperService,
                 settings: Settings = Settings.EMPTY):
        self.path = Path(shard_path)
        self.path.mkdir(parents=True, exist_ok=True)
        # engine incarnation id: distinguishes delete+recreate of the same
        # index/shard in caches keyed by reader generation (a recreated
        # engine restarts generations from 0)
        import uuid as _uuid
        self.engine_uuid = _uuid.uuid4().hex
        self.mapper_service = mapper_service
        self.settings = settings
        self.stats = EngineStats()
        self._lock = threading.RLock()
        self._closed = False
        # While pinned (counter: concurrent recoveries/snapshots may
        # overlap), flush/force-merge are refused so the committed file
        # set cannot change underneath a reader of those files: the
        # peer-recovery TARGET pins while a source streams a commit in,
        # and recovery sources/snapshot uploads pin while reading the
        # commit out (the reference holds an IndexCommit ref / blocks
        # flush on RECOVERING shards for the same windows).
        self._commit_pins = 0
        # wired by IndexService: threshold slow log (IndexingSlowLog.java)
        # and the node's breaker service for memory accounting
        self.indexing_slow_log = None
        self.breaker_service = None
        # Engine self-fail (Engine.failEngine, core/index/engine/
        # Engine.java maybeFailEngine): an IO error on the WAL or the
        # committed store closes the engine and reports the shard failed
        # so the master reallocates the copy — the fault must surface as
        # a shard failure, never a wedged shard. on_failure(reason) is
        # wired by IndexService; disk_fault is the store-write injection
        # hook (hook(op, None), op in {"store.write", "store.commit"}).
        self.on_failure = None
        self.failure_reason: str | None = None
        self.disk_fault = None
        # background merging (ElasticsearchConcurrentMergeScheduler +
        # MergePolicyConfig): refresh() checks the policy and submits a
        # merge to this executor (callable(fn); the node wires its "merge"
        # thread pool here — None runs the merge inline, which unit tests
        # and standalone engines want for determinism)
        self.merge_executor = None
        self._merge_running = False
        self._merge_failures = 0
        self._booted = False
        # reader-swap listeners (RefreshListeners analog): fired OUTSIDE
        # the engine lock after any operation that published a fresh
        # point-in-time view (refresh, background/force merge, segment
        # install). The collective plane hangs its double-buffered
        # data-layer rebuild here — the next generation's device pack
        # starts composing AT refresh, not at the first search.
        self.reader_swap_listeners: list = []

        if getattr(type(self), "_SHADOW", False):
            # read-only replica: no write handle on the primary's WAL,
            # no uncommitted-op replay (commits-only visibility)
            self.translog = _NullTranslog()
        else:
            durability = settings.get("index.translog.durability",
                                      DURABILITY_REQUEST)
            self.translog = Translog(self.path / "translog",
                                     durability=durability)

        self._segments: list[Segment] = []
        self._live_masks: list[np.ndarray] = []
        # segments installed with track_versions=False: the background
        # merge's per-row version-map re-check would silently drop their
        # (untracked) docs, so they never background-merge
        self._untracked_seg_ids: set[int] = set()
        self._buffer = SegmentBuilder(seg_id=0)
        self._buffer_docs: dict[str, int] = {}      # _id → buffer local doc
        self._versions: dict[str, VersionEntry] = {}
        # (seg_id, local_doc) → doc_id: committed copies superseded since the
        # last refresh; their live bits are cleared at the next refresh.
        self._pending_seg_deletes: dict[tuple[int, int], str] = {}
        self._next_seg_id = 1
        self._reader_gen = 0
        self._commit_gen = self._load_commit()
        self._replay_translog()
        # End recovery with a refresh (reference: recoverFromTranslog ends
        # with refresh, InternalEngine.java:215ff) so replayed ops — and
        # replayed *deletes* queued in _pending_seg_deletes — are visible to
        # the first searcher.
        self._reader = SearcherView([], [], 0)
        self.refresh()
        # merges stay off during construction: merge_executor is wired by
        # IndexService only after the engine exists, and recovery must not
        # block on an inline merge of a large commit
        self._booted = True

    # ------------------------------------------------------- engine self-fail

    def fail_engine(self, reason: str) -> None:
        """Close the engine and report the failure upward (failEngine):
        the IndexService callback turns this into a shard-failed report
        to the master, which reallocates the copy. Idempotent; the
        report runs OFF the failing op's thread because it walks cluster
        state and may submit a master update."""
        with self._lock:
            if self._closed or self.failure_reason is not None:
                return
            self.failure_reason = str(reason)
        cb = self.on_failure
        if cb is not None:
            t = threading.Thread(target=cb, args=(self.failure_reason,),
                                 name="engine-failure", daemon=True)
            t.start()
        try:
            self.close()
        except Exception:                        # noqa: BLE001 — dying disk
            pass

    def _fail_io(self, what: str, e: Exception) -> None:
        """An IO error on a durability-critical write: self-fail, then
        surface the retryable EngineClosedError so coordinators re-route
        to the copy the master promotes."""
        self.fail_engine(f"{what} failed: {e}")
        raise EngineClosedError(
            f"engine failed [{what} failed: {e}]") from e

    def _translog_add(self, op: TranslogOp, sync: bool) -> None:
        try:
            self.translog.add(op, sync=sync)
        except OSError as e:
            self._fail_io("translog append", e)

    def translog_sync(self) -> None:
        """Fsync the WAL per the durability policy; an IO error fails the
        engine (bulk callers ack only after this returns). On an engine
        that already failed mid-bulk this raises the retryable
        EngineClosedError so the coordinator re-routes the whole bulk to
        the promoted primary instead of surfacing a closed-file error."""
        self._ensure_open()
        try:
            self.translog.sync()
        except OSError as e:
            self._fail_io("translog sync", e)

    def _io_fault(self, op: str) -> None:
        fault = self.disk_fault
        if fault is not None:
            fault(op, None)                      # may raise OSError

    # ------------------------------------------------------------------ CRUD

    def index(self, doc_id: str, source: dict, version: int = MATCH_ANY,
              routing: str | None = None, op_type: str = "index",
              version_type: str = "internal",
              from_translog: bool = False,
              meta: dict | None = None,
              sync: bool = True) -> tuple[int, bool]:
        """→ (new_version, created). Version semantics follow
        InternalEngine.innerIndex (version check → write → versionMap put);
        version_type external/external_gte/force per VersionType.java —
        external compares against the LAST KNOWN version (tombstones
        included) and the doc takes the caller's version."""
        t0 = time.perf_counter()
        with self._lock:
            self._ensure_open()
            entry = self._versions.get(doc_id)
            current = NOT_FOUND if entry is None or entry.deleted else entry.version
            if version_type != "internal":
                _check_external_args(doc_id, version, version_type)
                known = NOT_FOUND if entry is None else entry.version
                ok = (version_type == "force"
                      or known == NOT_FOUND
                      or (version_type == "external_gte"
                          and version >= known)
                      or (version_type in ("external", "external_gt")
                          and version > known))
                if not ok:
                    raise VersionConflictError("", doc_id, known, version)
                new_version = version
            else:
                if op_type == "create" and current != NOT_FOUND:
                    raise VersionConflictError("", doc_id, current, 0)
                # internal versioning CONTINUES through tombstones
                # (InternalEngine.innerIndex loads deletes from the
                # version map: delete v11 → next index v12, and an
                # explicit expected version matches the tombstone's).
                # Restarting at 1 would break per-doc version
                # monotonicity — the property every replica/replay
                # "skip strictly-older ops" guard is built on.
                known = NOT_FOUND if entry is None else entry.version
                if version != MATCH_ANY and version != known:
                    raise VersionConflictError("", doc_id, known, version)
                new_version = 1 if known == NOT_FOUND else known + 1

            # stamp the resolved version into the doc's columns (the
            # VersionFieldMapper doc-value): fetched hits read the
            # point-in-time version from the SEGMENT, not the live map
            meta = dict(meta or {})
            meta["_version"] = new_version
            parsed = self.mapper_service.document_mapper(
                meta.get("_type")).parse(
                doc_id, source, routing=routing, meta=meta)
            # supersede any buffered copy of the same doc
            old_buf = self._buffer_docs.get(doc_id)
            if old_buf is not None:
                self._buffer.docs[old_buf] = None  # tombstone slot
            if entry is not None and entry.seg_id >= 0:
                self._pending_seg_deletes[(entry.seg_id, entry.local_doc)] = doc_id
            local = self._buffer.add(parsed)
            self._buffer_docs[doc_id] = local
            self._versions[doc_id] = VersionEntry(new_version, False, -1, local)
            if not from_translog:
                self._translog_add(TranslogOp(OP_INDEX, doc_id, new_version,
                                              source=source, routing=routing,
                                              meta=meta), sync)
            self.stats.index_total += 1
            took = time.perf_counter() - t0
            self.stats.index_time_ms += took * 1e3
            if self.indexing_slow_log is not None:
                self.indexing_slow_log.maybe_log(
                    took, f"id[{doc_id}], version[{new_version}]")
            return new_version, current == NOT_FOUND

    def index_replica(self, doc_id: str, source: dict, version: int,
                      routing: str | None = None,
                      meta: dict | None = None, sync: bool = True) -> int:
        """Apply a replicated index op with the version the primary
        resolved (TransportShardBulkAction replica path: no version
        conflict re-check, core/action/bulk/TransportShardBulkAction.java:448).
        Ops STRICTLY below the locally known version are skipped, which
        dedupes recovery-replay vs. live-replication overlap; an op AT
        the known version re-applies — that's idempotent for a double
        delivery of the same op, and required for external_gte, where two
        successive legitimate writes can carry the SAME version and the
        later one must win."""
        with self._lock:
            self._ensure_open()
            entry = self._versions.get(doc_id)
            if entry is not None and entry.version > version:
                return entry.version
            meta = dict(meta or {})
            meta["_version"] = version
            parsed = self.mapper_service.document_mapper(
                meta.get("_type")).parse(
                doc_id, source, routing=routing, meta=meta)
            old_buf = self._buffer_docs.get(doc_id)
            if old_buf is not None:
                self._buffer.docs[old_buf] = None
            if entry is not None and entry.seg_id >= 0:
                self._pending_seg_deletes[(entry.seg_id, entry.local_doc)] \
                    = doc_id
            local = self._buffer.add(parsed)
            self._buffer_docs[doc_id] = local
            self._versions[doc_id] = VersionEntry(version, False, -1, local)
            self._translog_add(TranslogOp(OP_INDEX, doc_id, version,
                                          source=source, routing=routing,
                                          meta=meta), sync)
            self.stats.index_total += 1
            return version

    def delete_replica(self, doc_id: str, version: int,
                       sync: bool = True) -> int:
        """Apply a replicated delete with the primary-resolved version
        (same strictly-below skip rule as index_replica: an equal-version
        delete — external_gte can issue one — must still apply)."""
        with self._lock:
            self._ensure_open()
            entry = self._versions.get(doc_id)
            if entry is not None and entry.version > version:
                return entry.version
            if entry is not None and entry.seg_id == -1:
                self._buffer.docs[entry.local_doc] = None
                self._buffer_docs.pop(doc_id, None)
            elif entry is not None and entry.seg_id >= 0:
                self._pending_seg_deletes[(entry.seg_id, entry.local_doc)] \
                    = doc_id
            self._versions[doc_id] = VersionEntry(version, True, -2, -1)
            self._translog_add(TranslogOp(OP_DELETE, doc_id, version), sync)
            self.stats.delete_total += 1
            return version

    def delete(self, doc_id: str, version: int = MATCH_ANY,
               version_type: str = "internal",
               from_translog: bool = False, sync: bool = True) -> int:
        with self._lock:
            self._ensure_open()
            entry = self._versions.get(doc_id)
            current = NOT_FOUND if entry is None or entry.deleted else entry.version
            if version_type != "internal":
                _check_external_args(doc_id, version, version_type)
                known = NOT_FOUND if entry is None else entry.version
                ok = (version_type == "force" or known == NOT_FOUND
                      or (version_type == "external_gte"
                          and version >= known)
                      or (version_type in ("external", "external_gt")
                          and version > known))
                if not ok:
                    raise VersionConflictError("", doc_id, known, version)
                if current == NOT_FOUND:
                    raise DocumentMissingError("", doc_id)
                new_version = version
            else:
                # same continuation rule as the index arm: explicit
                # internal versions compare against the LAST KNOWN
                # version, tombstones included
                known = NOT_FOUND if entry is None else entry.version
                if version != MATCH_ANY and version != known:
                    raise VersionConflictError("", doc_id, known, version)
                if current == NOT_FOUND:
                    raise DocumentMissingError("", doc_id)
                new_version = current + 1
            if entry.seg_id == -1:
                self._buffer.docs[entry.local_doc] = None
                self._buffer_docs.pop(doc_id, None)
            elif entry.seg_id >= 0:
                self._pending_seg_deletes[(entry.seg_id, entry.local_doc)] = doc_id
            self._versions[doc_id] = VersionEntry(new_version, True, -2, -1)
            if not from_translog:
                self._translog_add(TranslogOp(OP_DELETE, doc_id,
                                              new_version), sync)
            self.stats.delete_total += 1
            return new_version

    def doc_version(self, doc_id: str) -> int | None:
        """Current version of a live doc (None if absent/deleted) — feeds
        search hits' _version (version:true) and delete-by-query's
        optimistic per-doc deletes."""
        with self._lock:
            entry = self._versions.get(doc_id)
            if entry is None or entry.deleted:
                return None
            return entry.version

    def get(self, doc_id: str, realtime: bool = True) -> GetResult:
        """Realtime get (reference: ShardGetService.java:68 — reads from the
        version map / translog without waiting for refresh). With
        ``realtime=False``, the LAST REFRESHED view answers, like the
        reference's searcher-backed get: buffered writes and buffered
        deletes are invisible until refresh."""
        with self._lock:
            self._ensure_open()
            entry = self._versions.get(doc_id)
            if not realtime:
                return self._get_from_reader(doc_id, entry)
            if entry is None or entry.deleted:
                return GetResult(found=False, doc_id=doc_id)
            if entry.seg_id == -1:
                doc = self._buffer.docs[entry.local_doc]
                return GetResult(True, doc_id, entry.version, doc.source,
                                 meta=_parsed_meta(doc))
            for seg in self._segments:
                if seg.seg_id == entry.seg_id:
                    return GetResult(True, doc_id, entry.version,
                                     seg.sources[entry.local_doc],
                                     meta=_segment_meta(seg,
                                                        entry.local_doc))
            return GetResult(found=False, doc_id=doc_id)

    def _get_from_reader(self, doc_id: str,
                         entry: "VersionEntry | None") -> GetResult:
        """Non-realtime get: resolve through the current point-in-time
        view's segments + live masks (callers hold self._lock). The
        version reported is the segment row's own _version doc-value
        (the VersionFieldMapper column) — the point-in-time value, NOT
        the live map's, which may already be ahead of the refreshed
        view; rows without the column (legacy segments) fall back to the
        latest known version."""
        view = self._reader
        for seg, live in zip(view.segments, view.live_masks):
            index = getattr(seg, "_id_index", None)
            if index is None:
                index = {d: i for i, d in enumerate(seg.ids[:seg.num_docs])}
                seg._id_index = index
            local = index.get(doc_id)
            if local is not None and bool(live[local]):
                meta = _segment_meta(seg, local)
                if meta is not None and "_version" in meta:
                    version = int(meta["_version"])
                else:
                    version = entry.version if entry is not None else 1
                return GetResult(True, doc_id, version, seg.sources[local],
                                 meta=meta)
        return GetResult(found=False, doc_id=doc_id)

    # --------------------------------------------------------------- refresh

    def refresh(self) -> SearcherView:
        """Make buffered writes searchable: build a segment from the buffer,
        apply pending deletes to live bitmaps, swap the reader."""
        with self._lock:
            self._ensure_open()
            live_docs = [d for d in self._buffer.docs if d is not None]
            if live_docs:
                builder = SegmentBuilder(self._next_seg_id,
                                         max_tokens=self._buffer.max_tokens)
                for d in live_docs:
                    builder.add(d)
                seg = builder.build()
                mask = np.zeros(seg.padded_docs, dtype=bool)
                mask[:seg.num_docs] = True
                for local, d in enumerate(live_docs):
                    e = self._versions.get(d.doc_id)
                    if e is not None and not e.deleted and e.seg_id == -1:
                        self._versions[d.doc_id] = VersionEntry(
                            e.version, False, seg.seg_id, local)
                self._segments.append(seg)
                self._live_masks.append(mask)
                self._next_seg_id += 1
                self._buffer = SegmentBuilder(seg_id=0,
                                              max_tokens=self._buffer.max_tokens)
                self._buffer_docs = {}
            # apply deletes & updates to committed segments (only docs whose
            # committed copy was superseded since the last refresh)
            if self._pending_seg_deletes:
                by_seg = {s.seg_id: (s, m) for s, m in
                          zip(self._segments, self._live_masks)}
                for (seg_id, local), did in self._pending_seg_deletes.items():
                    pair = by_seg.get(seg_id)
                    if pair is None:
                        continue
                    seg, mask = pair
                    e = self._versions.get(did)
                    if e is None or e.deleted or e.seg_id != seg_id \
                            or e.local_doc != local:
                        mask[local] = False
                self._pending_seg_deletes = {}
            self.stats.refresh_total += 1
            out = self._swap_reader()
        self._maybe_merge()
        self._notify_reader_swap()
        return out

    def _notify_reader_swap(self) -> None:
        """Fire reader-swap listeners outside the engine lock (a listener
        scheduling a device pack rebuild may itself acquire searcher
        views). Listener failures never fail the swap."""
        for cb in list(self.reader_swap_listeners):
            try:
                cb()
            except Exception:                # noqa: BLE001 — best-effort
                pass

    def _swap_reader(self) -> SearcherView:
        """Bump the generation and publish a fresh point-in-time view
        (callers hold self._lock)."""
        self._reader_gen += 1
        self._reader = SearcherView(list(self._segments),
                                    [m.copy() for m in self._live_masks],
                                    self._reader_gen)
        return self._reader

    def install_segment(self, segment: Segment,
                        track_versions: bool = True) -> None:
        """Bulk-ingest: install a pre-built immutable segment into the live
        segment set and swap the reader — the engine-level analog of
        Lucene's ``IndexWriter.addIndexes`` (used for bulk loads that
        build columnar segments directly, e.g. Segment.from_packed_text).

        Documents are taken as NEW: no version-conflict checks run. With
        ``track_versions=False`` the version map skips them (append-only
        corpora: realtime get / update / delete-by-id won't resolve these
        docs). The segment is NOT in the translog — call :meth:`flush` to
        make the install durable (addIndexes has the same contract: files
        are only safe after commit)."""
        with self._lock:
            self._ensure_open()
            segment.seg_id = self._next_seg_id
            self._next_seg_id += 1
            mask = np.zeros(segment.padded_docs, dtype=bool)
            mask[:segment.num_docs] = True
            if track_versions:
                for local in range(segment.num_docs):
                    self._versions[segment.ids[local]] = VersionEntry(
                        1, False, segment.seg_id, local)
            else:
                self._untracked_seg_ids.add(segment.seg_id)
            self._segments.append(segment)
            self._live_masks.append(mask)
            self.stats.index_total += segment.num_docs
            self._swap_reader()
        self._notify_reader_swap()

    def acquire_searcher(self) -> SearcherView:
        with self._lock:
            self._ensure_open()
            return self._reader

    # ----------------------------------------------------------------- flush

    def flush(self) -> None:
        """Persist segments + commit point; roll translog
        (InternalEngine.java:616: Lucene commit + translog roll)."""
        with self._lock:
            self._ensure_open()
            if self._commit_pins:
                return                           # commit pinned — no flush
            self.refresh()
            store_type = str(self.settings.get("index.store.type", "fs"))
            try:
                for seg, mask in zip(self._segments, self._live_masks):
                    self._io_fault("store.write")
                    seg_dir = self.path / f"seg_{seg.seg_id}"
                    if not (seg_dir / "meta.json").exists():
                        seg.write(seg_dir, store_type=store_type)
                    np.save(seg_dir / "live.tmp.npy", mask)
                    os.replace(seg_dir / "live.tmp.npy",
                               seg_dir / "live.npy")
                self._commit_gen += 1
                commit = {
                    "generation": self._commit_gen,
                    "segments": [s.seg_id for s in self._segments],
                    "next_seg_id": self._next_seg_id,
                    "versions": {did: [e.version, e.deleted, e.seg_id,
                                       e.local_doc]
                                 for did, e in self._versions.items()},
                }
                self._io_fault("store.commit")
                tmp = self.path / "commit.json.tmp"
                tmp.write_text(json.dumps(commit))
                os.replace(tmp, self.path / "commit.json")
                self.translog.roll(committed=True)
            except OSError as e:
                # a failed commit leaves the previous commit.json intact
                # (tmp + atomic replace), but the engine's durability
                # contract is broken — self-fail and reallocate
                self._fail_io("store commit", e)
            self.stats.flush_total += 1

    # ------------------------------------------------- background merging

    def _merge_candidates(self) -> list[tuple[Segment, "np.ndarray"]]:
        """Merge policy (MergePolicyConfig, tiered-lite): once the segment
        count exceeds segments_per_tier, merge up to max_merge_at_once of
        the SMALLEST re-analyzable segments into one. Two tiered-style
        guards keep total merge work O(n log n) instead of O(n²): segments
        above max_merged_segment_docs never merge again, and a run of
        small segments won't drag in a segment >4× their combined size
        (so the accumulated big segment isn't rewritten every cycle).
        Callers hold _lock."""
        per_tier = int(self.settings.get(
            "index.merge.policy.segments_per_tier", 10))
        max_at_once = int(self.settings.get(
            "index.merge.policy.max_merge_at_once", 10))
        max_merged = int(self.settings.get(
            "index.merge.policy.max_merged_segment_docs", 5_000_000))
        if len(self._segments) <= per_tier:
            return []
        cands = [(s, m) for s, m in zip(self._segments, self._live_masks)
                 if s.source_complete
                 and s.seg_id not in self._untracked_seg_ids
                 and s.num_docs < max_merged]
        if len(cands) < 2:
            return []
        cands.sort(key=lambda sm: sm[0].num_docs)
        picked: list = []
        total = 0
        for s, m in cands:
            if picked and s.num_docs > 4 * max(total, 64):
                break                      # size skew: stop before the jump
            picked.append((s, m))
            total += s.num_docs
            if len(picked) == max_at_once:
                break
        return picked if len(picked) >= 2 else []

    def _maybe_merge(self) -> None:
        """Refresh-time merge trigger (the scheduler seam the reference
        hangs off IndexWriter; ours hangs off refresh because that is when
        new segments appear)."""
        with self._lock:
            if (not self._booted or self._closed or self._commit_pins
                    or self._merge_running or self._merge_failures >= 3
                    or not self._merge_candidates()):
                return
            self._merge_running = True
        if self.merge_executor is not None:
            try:
                self.merge_executor(self._background_merge)
            except Exception:                # noqa: BLE001 — pool closed
                self._merge_running = False
        else:
            self._background_merge()

    def _background_merge(self) -> None:
        """One background merge: snapshot the candidate segments under the
        lock, re-analyze them into one OUTSIDE the lock (writes continue),
        then commit the swap — docs deleted or updated during the merge
        stay dead because the version map is re-checked per row at commit
        (Lucene carries deletes forward into merged segments the same
        way). Failures log and count toward a circuit breaker (3 strikes
        stops retriggering; a successful force_merge resets it) so a
        persistently unmergeable segment can't wedge refresh or spin the
        merge pool."""
        try:
            with self._lock:
                if self._closed or self._commit_pins:
                    return
                cands = self._merge_candidates()
                if not cands:
                    return
                srcs = [(s, m.copy()) for s, m in cands]
            builder = merge_segments(
                0, [s for s, _ in srcs], [m for _, m in srcs],
                self.mapper_service.document_mapper(),
                max_tokens=self._buffer.max_tokens)
            merged = builder.build()
            # row → source location, in merge_segments' iteration order
            locs = [(s.seg_id, local) for s, m in srcs
                    for local in range(s.num_docs) if m[local]]
            with self._lock:
                if self._closed or self._commit_pins:
                    return
                present = {s.seg_id for s in self._segments}
                if not all(s.seg_id in present for s, _ in srcs):
                    return               # raced with a force_merge
                merged.seg_id = self._next_seg_id
                self._next_seg_id += 1
                mask = np.zeros(merged.padded_docs, dtype=bool)
                for local, (ssid, slocal) in enumerate(locs):
                    e = self._versions.get(merged.ids[local])
                    if e is not None and not e.deleted \
                            and e.seg_id == ssid and e.local_doc == slocal:
                        mask[local] = True
                        self._versions[merged.ids[local]] = VersionEntry(
                            e.version, False, merged.seg_id, local)
                drop = {s.seg_id for s, _ in srcs}
                keep = [i for i, s in enumerate(self._segments)
                        if s.seg_id not in drop]
                self._segments = [self._segments[i] for i in keep] + [merged]
                self._live_masks = [self._live_masks[i]
                                    for i in keep] + [mask]
                self._pending_seg_deletes = {
                    k: v for k, v in self._pending_seg_deletes.items()
                    if k[0] not in drop}
                self.stats.merge_total += 1
                self._swap_reader()
                self._drop_segment_files(drop)
            self._merge_failures = 0
            self._notify_reader_swap()
        except Exception:                    # noqa: BLE001 — see docstring
            import logging
            self._merge_failures += 1
            logging.getLogger(__name__).exception(
                "background merge failed (%d/3) on %s",
                self._merge_failures, self.path)
        finally:
            self._merge_running = False

    def _drop_segment_files(self, drop_ids) -> None:
        """Persist the post-merge commit FIRST (when any dropped segment
        was committed), then delete the merged-away directories — a crash
        in between must never lose committed docs. Callers hold _lock."""
        was_committed = any(
            (self.path / f"seg_{sid}" / "meta.json").exists()
            for sid in drop_ids)
        if was_committed:
            self.flush()
        import shutil
        for sid in drop_ids:
            seg_dir = self.path / f"seg_{sid}"
            if seg_dir.exists():
                shutil.rmtree(seg_dir)

    def synced_flush(self, sync_id: str | None = None) -> str | None:
        """Flush + stamp a sync_id in the commit (SyncedFlushService.java:
        60). Every COPY of a shard must receive the SAME id (the broadcast
        coordinator generates one) — matching ids are the cheap proof of
        file identity; our recovery also diffs by checksum, so the id is a
        marker, not a correctness requirement."""
        import uuid as _uuid
        with self._lock:
            self._ensure_open()
            if self._commit_pins:
                return None
            self.flush()
            commit_file = self.path / "commit.json"
            if not commit_file.exists():
                return None
            commit = json.loads(commit_file.read_text())
            sync_id = sync_id or _uuid.uuid4().hex
            commit["sync_id"] = sync_id
            tmp = self.path / "commit.json.tmp"
            tmp.write_text(json.dumps(commit))
            os.replace(tmp, commit_file)
            return sync_id

    def buffer_memory_bytes(self) -> int:
        """Rough RAM footprint of the uncommitted write buffer — the
        figure the IndexingMemoryController budget governs (the analog of
        Lucene's DocumentsWriter RAM accounting)."""
        with self._lock:
            total = 0
            for doc in self._buffer.docs:
                if doc is None:
                    continue
                total += 256                      # per-doc fixed overhead
                for pf in doc.fields.values():
                    total += 16 * len(pf.tokens) + 24 * len(pf.keywords) \
                        + 8 * len(pf.numerics)
                    if pf.vector is not None:
                        total += pf.vector.nbytes
            return total

    def expired_docs(self, now_ms: int) -> list[str]:
        """Doc ids whose _ttl expiry passed (the IndicesTTLService sweep
        source, core/indices/ttl/IndicesTTLService.java — there a range
        query over _ttl; here a direct scan of the numeric column +
        write buffer)."""
        out: list[str] = []
        with self._lock:
            for seg, live in zip(self._segments, self._live_masks):
                col = seg.numeric_fields.get("_ttl")
                if col is None:
                    continue
                vals = np.asarray(col.values[:seg.num_docs])
                ex = np.asarray(col.exists[:seg.num_docs])
                mask = ex & (vals <= now_ms) & live[:seg.num_docs]
                for local in np.nonzero(mask)[0]:
                    did = seg.ids[int(local)]
                    entry = self._versions.get(did)
                    if entry is not None and not entry.deleted and \
                            entry.seg_id == seg.seg_id and \
                            entry.local_doc == int(local):
                        out.append(did)
            for did, local in self._buffer_docs.items():
                doc = self._buffer.docs[local]
                if doc is None:
                    continue
                f = doc.fields.get("_ttl")
                if f is not None and f.numerics and \
                        f.numerics[0] <= now_ms:
                    out.append(did)
        return out

    def commit_user_data(self) -> dict:
        """The last commit's user data (ref: SegmentInfos userData — where
        the reference stamps translog ids and the synced-flush sync_id)."""
        commit_file = self.path / "commit.json"
        if not commit_file.exists():
            return {}
        try:
            commit = json.loads(commit_file.read_text())
        except (OSError, json.JSONDecodeError):
            return {}
        out = {"translog_generation": str(commit.get("translog_gen", 0))}
        if commit.get("sync_id"):
            out["sync_id"] = commit["sync_id"]
        return out

    def force_merge(self, max_num_segments: int = 1) -> None:
        """_optimize / force-merge: rewrite segments into one, dropping
        deleted docs (ElasticsearchConcurrentMergeScheduler's job)."""
        with self._lock:
            self._ensure_open()
            if self._commit_pins:
                return                           # commit pinned — no merge
            self.refresh()
            if len(self._segments) <= max_num_segments:
                return
            # bulk-ingested segments without stored _source cannot be
            # re-analyzed, and untracked ones would lose every doc to the
            # version-map re-check — keep both as-is, merge only the rest
            # (kept MUST be the exact complement of mergeable: a segment
            # in neither list would silently vanish from the index)
            def can_merge(s: Segment) -> bool:
                return s.source_complete and \
                    s.seg_id not in self._untracked_seg_ids
            mergeable = [(s, m) for s, m in
                         zip(self._segments, self._live_masks)
                         if can_merge(s)]
            kept = [(s, m) for s, m in zip(self._segments, self._live_masks)
                    if not can_merge(s)]
            if len(mergeable) <= 1:
                return
            builder = merge_segments(self._next_seg_id,
                                     [s for s, _ in mergeable],
                                     [m for _, m in mergeable],
                                     self.mapper_service.document_mapper(),
                                     max_tokens=self._buffer.max_tokens)
            merged = builder.build()
            mask = np.zeros(merged.padded_docs, dtype=bool)
            mask[:merged.num_docs] = True
            for local, did in enumerate(merged.ids):
                e = self._versions.get(did)
                if e is not None and not e.deleted:
                    self._versions[did] = VersionEntry(e.version, False,
                                                       merged.seg_id, local)
            old = [s for s, _ in mergeable]
            self._segments = [s for s, _ in kept] + [merged]
            self._live_masks = [m for _, m in kept] + [mask]
            self._next_seg_id += 1
            self.stats.merge_total += 1
            self._merge_failures = 0
            self._swap_reader()
            self._drop_segment_files([seg.seg_id for seg in old])
        self._notify_reader_swap()

    # -------------------------------------------------------------- recovery

    def _load_commit(self) -> int:
        commit_file = self.path / "commit.json"
        if not commit_file.exists():
            return 0
        commit = json.loads(commit_file.read_text())
        for seg_id in commit["segments"]:
            seg_dir = self.path / f"seg_{seg_id}"
            seg = Segment.read(seg_dir)
            live_file = seg_dir / "live.npy"
            mask = (np.load(live_file) if live_file.exists()
                    else np.concatenate([np.ones(seg.num_docs, bool),
                                         np.zeros(seg.padded_docs - seg.num_docs,
                                                  bool)]))
            self._segments.append(seg)
            self._live_masks.append(mask)
        self._next_seg_id = commit["next_seg_id"]
        self._versions = {
            did: VersionEntry(v[0], v[1], v[2], v[3])
            for did, v in commit["versions"].items()}
        return commit["generation"]

    def _replay_translog(self) -> None:
        for op in self.translog.uncommitted_ops():
            if op.op == OP_INDEX:
                # apply UNCONDITIONALLY: the translog is the total order
                # of this shard's ops, and the committed state reflects a
                # prefix of it, so replaying every op in sequence
                # converges to the exact pre-crash state — version-based
                # skips can't express "later in the log" once force
                # writes (which may LOWER a version) or external_gte
                # equal-version successors are in play
                self._apply_replayed_index(op)
            elif op.op == OP_DELETE:
                entry = self._versions.get(op.doc_id)
                if entry is not None and entry.seg_id == -1:
                    self._buffer.docs[entry.local_doc] = None
                    self._buffer_docs.pop(op.doc_id, None)
                elif entry is not None and entry.seg_id >= 0:
                    self._pending_seg_deletes[(entry.seg_id, entry.local_doc)] \
                        = op.doc_id
                self._versions[op.doc_id] = VersionEntry(op.version, True, -2, -1)

    def _apply_replayed_index(self, op: TranslogOp) -> None:
        meta = dict(op.meta or {})
        meta["_version"] = op.version
        parsed = self.mapper_service.document_mapper(
            meta.get("_type")).parse(
            op.doc_id, op.source, routing=op.routing, meta=meta)
        old_buf = self._buffer_docs.get(op.doc_id)
        if old_buf is not None:
            self._buffer.docs[old_buf] = None
        prev = self._versions.get(op.doc_id)
        if prev is not None and prev.seg_id >= 0:
            self._pending_seg_deletes[(prev.seg_id, prev.local_doc)] = op.doc_id
        local = self._buffer.add(parsed)
        self._buffer_docs[op.doc_id] = local
        self._versions[op.doc_id] = VersionEntry(op.version, False, -1, local)

    @property
    def recovery_in_progress(self) -> bool:
        return self._commit_pins > 0

    def pin_commit(self, flush_first: bool = True) -> None:
        """Freeze the committed file set (refuse flush/merge) until
        unpin_commit — atomic under the engine lock so no merge can slip
        between the flush and the pin. Counted: overlapping pins stack."""
        with self._lock:
            self._ensure_open()
            if flush_first and self._commit_pins == 0:
                self.flush()
            self._commit_pins += 1

    def unpin_commit(self) -> None:
        with self._lock:
            self._commit_pins = max(0, self._commit_pins - 1)

    # ------------------------------------------------ peer recovery (source)

    def file_manifest(self) -> dict[str, list[int]]:
        """Relative path → [size, crc32] of every committed file (commit
        point + segment files). The analog of Store.MetadataSnapshot
        (core/index/store/Store.java:87) — the checksum diff that lets
        phase1 skip files the target already holds."""
        import zlib
        with self._lock:
            self._ensure_open()
            out: dict[str, list[int]] = {}
            commit = self.path / "commit.json"
            files = [commit] if commit.exists() else []
            for seg_dir in sorted(self.path.glob("seg_*")):
                # recursive: nested child blocks live in subdirectories
                files.extend(sorted(p for p in seg_dir.rglob("*")
                                    if p.is_file()))
            for f in files:
                data = f.read_bytes()
                out[str(f.relative_to(self.path))] = \
                    [len(data), zlib.crc32(data) & 0xFFFFFFFF]
            return out

    # ------------------------------------------------ peer recovery (target)

    def install_recovered_commit(self) -> None:
        """Swap in a commit whose files phase1 just wrote under this
        engine's path, discarding all in-memory state. Safe against live
        replicated writes racing the file copy: any op newer than the
        source's commit is re-delivered by phase2 translog replay (version-
        deduped), any older op is already inside the commit."""
        with self._lock:
            self._ensure_open()
            self._segments = []
            self._live_masks = []
            self._buffer = SegmentBuilder(seg_id=0,
                                          max_tokens=self._buffer.max_tokens)
            self._buffer_docs = {}
            self._versions = {}
            self._pending_seg_deletes = {}
            self._commit_gen = self._load_commit()
            # everything before the installed commit is superseded — mark
            # the local translog committed so restart-replay can't
            # resurrect pre-recovery ops
            self.translog.roll(committed=True)
            self.refresh()

    # ------------------------------------------------------------- lifecycle

    @property
    def num_docs(self) -> int:
        with self._lock:
            return sum(1 for e in self._versions.values() if not e.deleted)

    def segment_stats(self) -> list[dict]:
        return [{"seg_id": s.seg_id, "num_docs": s.num_docs,
                 "live_docs": int(m[:s.num_docs].sum()),
                 "memory_bytes": s.memory_bytes()}
                for s, m in zip(self._segments, self._live_masks)]

    def _ensure_open(self) -> None:
        if self._closed:
            raise EngineClosedError("engine is closed")

    def close(self) -> None:
        with self._lock:
            if not self._closed:
                # return the cached device reader's breaker reservation
                from elasticsearch_tpu.index.device_reader import (
                    release_device_reader)
                release_device_reader(self)
                # collective-plane packs (and anything else holding
                # device memory against this engine's segments) release
                # through close listeners — breaker balance must hold
                # the moment the ENGINE dies, not only at index close
                for cb in list(getattr(self, "_close_listeners", ())):
                    try:
                        cb()
                    except Exception:    # noqa: BLE001 — teardown path
                        pass
                self.translog.close()
                self._closed = True


class _NullTranslog:
    """The shadow's translog stand-in: a read-only replica must neither
    hold a write handle on the primary's WAL nor replay uncommitted ops
    (ShadowEngine reads COMMITS only)."""

    generation = 0
    committed_generation = 0

    def add(self, *a, **kw):
        raise EngineClosedError("shadow engine has no translog")

    def uncommitted_ops(self):
        return []

    def roll(self, *a, **kw):
        return None

    def sync(self):
        return None

    def stats(self):
        return {"operations": 0, "size_in_bytes": 0}

    def close(self):
        return None


class ShadowEngine(Engine):
    """Read-only engine over a shared-filesystem shard directory (ref:
    core/index/engine/ShadowEngine.java — with index.shadow_replicas,
    replicas never apply ops; they re-open the commits the primary wrote
    to shared storage). Document ops, flush, and merges are refused — the
    PRIMARY owns the directory's commit and translog; the shadow only
    ever reads committed state. ``refresh_from_disk`` picks up the
    primary's latest commit."""

    _SHADOW = True

    def index(self, *a, **kw):
        raise EngineClosedError(
            "shadow engine does not support document operations")

    index_replica = index
    delete = index
    delete_replica = index

    def flush(self, *a, **kw):
        # committing from the shadow would overwrite the primary's commit
        # and (worse) roll its translog — ShadowEngine.flush is a no-op
        # reader re-open in the reference too
        return None

    def force_merge(self, *a, **kw):
        raise EngineClosedError("shadow engine does not merge")

    def _maybe_merge(self, *a, **kw):
        # a shadow merging would rewrite — and then DELETE — segment
        # directories the PRIMARY's commit still references on the shared
        # filesystem; merging is the primary's job alone
        return None

    def synced_flush(self, *a, **kw):
        return None

    def refresh_from_disk(self) -> int:
        """Re-open the newest on-disk commit (the primary's flush) and
        swap the reader. → the commit generation now serving reads."""
        with self._lock:
            self._ensure_open()
            self._segments = []
            self._live_masks = []
            self._buffer = SegmentBuilder(
                seg_id=0, max_tokens=self._buffer.max_tokens)
            self._buffer_docs = {}
            self._versions = {}
            self._pending_seg_deletes = {}
            self._commit_gen = self._load_commit()
            self.refresh()
            return self._commit_gen
