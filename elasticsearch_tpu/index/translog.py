"""Translog — the per-shard write-ahead log.

Mirrors the reference's durability design (core/index/translog/Translog.java):
an append-only sequence of checksummed frames split into **generations**
(``translog-<gen>.tlog`` files), with an atomically-updated ``translog.ckp``
checkpoint recording the current generation/offset/op-count
(Translog.java:179,273-276). Ops are added on every index/delete
(Translog.java:474); ``sync`` fsyncs per the durability policy
(REQUEST | ASYNC, Translog.java:1367); a flush (Lucene commit) rolls to a new
generation and trims ones below the commit point.

Frame format: ``[length u32][crc32 u32][payload bytes]`` where payload is a
compact JSON op record. CRC failures raise :class:`TranslogCorruptedError`
during replay (recovery stops at the first torn/corrupt tail frame, matching
the reference's truncated-translog handling).
"""

from __future__ import annotations

import json
import os
import threading
import struct
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Iterator

from elasticsearch_tpu.common.errors import TranslogCorruptedError

OP_INDEX = "index"
OP_DELETE = "delete"

DURABILITY_REQUEST = "request"  # fsync on every write
DURABILITY_ASYNC = "async"      # fsync on interval / flush only

_HEADER = struct.Struct("<II")
_CKP_MAGIC = "es-tpu-translog-ckp"


@dataclass
class TranslogOp:
    op: str                    # OP_INDEX | OP_DELETE
    doc_id: str
    version: int
    source: dict | None = None
    routing: str | None = None
    seq_no: int = -1
    # metadata fields (_type/_parent/_timestamp/_ttl) — replayed so a
    # restart preserves parent joins and TTL expiries
    meta: dict | None = None

    def encode(self) -> bytes:
        rec: dict[str, Any] = {"op": self.op, "id": self.doc_id,
                               "v": self.version, "seq": self.seq_no}
        if self.source is not None:
            rec["src"] = self.source
        if self.routing is not None:
            rec["r"] = self.routing
        if self.meta:
            rec["m"] = self.meta
        return json.dumps(rec, separators=(",", ":")).encode("utf-8")

    @staticmethod
    def decode(data: bytes) -> "TranslogOp":
        rec = json.loads(data)
        return TranslogOp(op=rec["op"], doc_id=rec["id"], version=rec["v"],
                          source=rec.get("src"), routing=rec.get("r"),
                          seq_no=rec.get("seq", -1), meta=rec.get("m"))


class Translog:
    def __init__(self, path: Path, durability: str = DURABILITY_REQUEST):
        self.path = Path(path)
        self.path.mkdir(parents=True, exist_ok=True)
        self.durability = durability
        # Disk-fault injection seam (the MockDirectoryWrapper analog for
        # the WAL): hook(op, data) called before every append ("add",
        # frame bytes) and fsync ("sync", None). It may raise OSError to
        # inject an IO error, or — for "add" — return a truncated frame
        # to simulate a short (torn) write: the truncated bytes land in
        # the file and the append still fails. None in production.
        self.fault_hook = None
        gen, committed_gen, seq_no = self._read_checkpoint()
        self.generation = gen
        self.committed_generation = committed_gen
        self.next_seq_no = seq_no
        # A crash mid-append can leave a torn frame at the tail. Replay stops
        # at the first torn frame, so appending after one would make every
        # later (acked, fsynced) op unreachable — truncate to the last valid
        # frame boundary before reopening for append (the reference recovers
        # to the checkpointed offset; Translog.java:273-276).
        self._ops_in_gen = self._truncate_to_valid(self.generation)
        self._file = open(self._gen_path(self.generation), "ab")
        self._views: list[int] = []              # pinned view start gens
        # serializes view bookkeeping against roll/trim: an unsynchronized
        # acquire_view racing a concurrent flush could register a view for
        # generations _trim already deleted, silently losing phase2 ops
        self._views_lock = threading.Lock()

    # ---- files ------------------------------------------------------------

    def _gen_path(self, gen: int) -> Path:
        return self.path / f"translog-{gen}.tlog"

    def _ckp_path(self) -> Path:
        return self.path / "translog.ckp"

    def _read_checkpoint(self) -> tuple[int, int, int]:
        ckp = self._ckp_path()
        if not ckp.exists():
            return 1, 0, 0
        rec = json.loads(ckp.read_text())
        if rec.get("magic") != _CKP_MAGIC:
            raise TranslogCorruptedError(f"bad checkpoint magic in {ckp}")
        return rec["generation"], rec["committed_generation"], rec["seq_no"]

    def _write_checkpoint(self) -> None:
        tmp = self._ckp_path().with_suffix(".ckp.tmp")
        tmp.write_text(json.dumps({
            "magic": _CKP_MAGIC, "generation": self.generation,
            "committed_generation": self.committed_generation,
            "seq_no": self.next_seq_no}))
        os.replace(tmp, self._ckp_path())

    def _truncate_to_valid(self, gen: int) -> int:
        """Scan generation ``gen``; truncate any torn tail frame. Returns the
        number of valid ops. Raises on mid-file checksum corruption."""
        p = self._gen_path(gen)
        if not p.exists():
            return 0
        valid_end = 0
        ops = 0
        with open(p, "rb") as f:
            while True:
                header = f.read(_HEADER.size)
                if len(header) < _HEADER.size:
                    break
                length, crc = _HEADER.unpack(header)
                payload = f.read(length)
                if len(payload) < length:
                    break  # torn tail
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    raise TranslogCorruptedError(
                        f"translog checksum mismatch in {p.name}")
                valid_end += _HEADER.size + length
                ops += 1
        if p.stat().st_size > valid_end:
            with open(p, "r+b") as f:
                f.truncate(valid_end)
        return ops

    # ---- write path -------------------------------------------------------

    def add(self, op: TranslogOp, sync: bool = True) -> int:
        """Append one op; returns its seq_no. With ``sync`` (the default)
        REQUEST durability fsyncs immediately; bulk callers pass
        sync=False per op and call :meth:`sync` ONCE before acking — the
        reference's per-REQUEST (not per-op) durability
        (TransportShardBulkAction syncs the translog once per shard bulk,
        IndexShard.sync). One fsync per 4k-doc bulk instead of 4k."""
        op.seq_no = self.next_seq_no
        payload = op.encode()
        frame = _HEADER.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF) + payload
        fault = self.fault_hook
        if fault is not None:
            torn = fault("add", frame)           # may raise OSError
            if torn is not None:
                # short write: the torn prefix reaches the file, then the
                # append fails — replay must stop at the frame boundary
                self._file.write(torn)
                self._file.flush()
                raise OSError(
                    f"simulated short write ({len(torn)}/{len(frame)} "
                    f"bytes)")
        self._file.write(frame)
        self.next_seq_no += 1
        self._ops_in_gen += 1
        if sync and self.durability == DURABILITY_REQUEST:
            self.sync()
        return op.seq_no

    def stats(self) -> dict:
        """Uncommitted operation count + on-disk bytes of live generations
        (the _stats translog section)."""
        ops = len(self.uncommitted_ops())
        size = 0
        for p in self.path.glob("translog-*.tlog"):
            try:
                size += p.stat().st_size
            except OSError:
                pass
        return {"operations": ops, "size_in_bytes": size}

    def sync(self) -> None:
        if self._file.closed:
            # closed by a concurrent engine self-fail: surface the IO
            # class the callers handle, not ValueError from flush()
            raise OSError("translog closed")
        fault = self.fault_hook
        if fault is not None:
            fault("sync", None)                  # may raise OSError
        self._file.flush()
        os.fsync(self._file.fileno())
        self._write_checkpoint()

    # ---- read / replay ----------------------------------------------------

    def read_generation(self, gen: int) -> Iterator[TranslogOp]:
        p = self._gen_path(gen)
        if not p.exists():
            return
        with open(p, "rb") as f:
            while True:
                header = f.read(_HEADER.size)
                if not header:
                    return
                if len(header) < _HEADER.size:
                    return  # torn tail write — stop (crash during append)
                length, crc = _HEADER.unpack(header)
                payload = f.read(length)
                if len(payload) < length:
                    return  # torn tail
                if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                    raise TranslogCorruptedError(
                        f"translog checksum mismatch in {p.name}")
                yield TranslogOp.decode(payload)

    def uncommitted_ops(self) -> list[TranslogOp]:
        """All ops in generations newer than the last commit (replayed on
        engine open — InternalEngine.java:215 recoverFromTranslog)."""
        return self.ops_since(self.committed_generation)

    def ops_since(self, gen: int) -> list[TranslogOp]:
        """All ops in generations newer than ``gen`` (peer-recovery phase2
        reads the ops captured during the file copy through a view —
        Translog snapshot/views, core/index/translog/Translog.java:506)."""
        self._file.flush()
        ops: list[TranslogOp] = []
        for g in range(gen + 1, self.generation + 1):
            ops.extend(self.read_generation(g))
        return ops

    # ---- views (pin generations open during peer recovery) -----------------

    def acquire_view(self) -> int:
        """Pin every generation after the current commit so a concurrent
        flush/roll can't trim them while a recovery streams files; returns
        the generation the view starts after (pass to ops_since)."""
        with self._views_lock:
            view_from = self.committed_generation
            self._views.append(view_from)
            return view_from

    def release_view(self, view_from: int) -> None:
        with self._views_lock:
            try:
                self._views.remove(view_from)
            except ValueError:
                pass
            self._trim()

    @property
    def num_uncommitted(self) -> int:
        return len(self.uncommitted_ops())

    # ---- lifecycle --------------------------------------------------------

    def roll(self, committed: bool = True) -> None:
        """Start a new generation; called by flush after the commit point is
        durable. Trims generations at/below the commit (Translog trimming)."""
        self.sync()
        self._file.close()
        if committed:
            self.committed_generation = self.generation
        self.generation += 1
        self._file = open(self._gen_path(self.generation), "ab")
        self._ops_in_gen = 0
        self._write_checkpoint()
        with self._views_lock:
            self._trim()

    def _trim(self) -> None:
        """Delete generations at/below the commit point, except ones a
        recovery view still needs. Caller holds _views_lock."""
        keep_after = min([self.committed_generation] + list(self._views))
        for p in self.path.glob("translog-*.tlog"):
            try:
                gen = int(p.stem.split("-")[1])
            except (IndexError, ValueError):
                continue
            if gen <= keep_after:
                p.unlink(missing_ok=True)

    def close(self) -> None:
        if not self._file.closed:
            try:
                self.sync()
            except OSError:
                # a failing disk must not wedge close — the engine is
                # self-failing; acked ops were already synced per policy
                pass
            self._file.close()
