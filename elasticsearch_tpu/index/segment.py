"""The columnar segment — this framework's Lucene-equivalent index format.

The reference's per-shard index is a set of immutable Lucene segments
(postings lists + doc values + stored fields; written by IndexWriter, read
via NRT readers — core/index/engine/InternalEngine.java). Pointer-chasing,
variable-length postings don't map to XLA/TPU, so the segment here is a set
of **dense, padded, fixed-shape matrices** designed for HBM residency and
vectorized scoring (SURVEY.md §7 step 2, BM25S-style eager scoring,
PAPERS.md):

Per analyzed text field:
  * ``tokens[N, L]`` int32 — **position-indexed**: slot ``p`` holds the term
    id at token position ``p`` (-1 for holes left by stopword removal, array
    gaps, and padding). Phrase matching with position gaps becomes a pure
    shifted dense compare (ops/phrase.py), replacing Lucene's position
    postings.
  * ``uterms[N, U]`` int32 / ``utf[N, U]`` float32 — unique terms per doc and
    their term frequencies: the *forward impact index*. BM25 scoring reads
    these as dense vector ops (no scatter); equivalent of the term-frequency
    postings + norms that Lucene's TermScorer/BM25Similarity consume.
  * per-segment term dictionary + ``df`` counts (idf is computed at query
    time from df aggregated across segments/shards, matching Lucene's
    query-time IDF and enabling the DFS distributed-stats mode).

Per keyword field: sorted vocab + ordinal matrix ``ords[N, K]`` (-1 pad) —
the equivalent of SORTED_SET doc values (ordinal order == lexical order, so
range/sort/terms-agg work on ordinals).

Per numeric field: ``values[N]`` float64 + ``exists[N]`` — NUMERIC doc values.
Per dense_vector field: ``vecs[N, D]`` float32 — row-major for MXU matmuls.

All row counts are padded to tiling-friendly multiples; readers carry the
true ``num_docs``. Segments are immutable after build; deletes live in the
engine as per-segment live-bitmaps (Lucene's .liv files).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field as dc_field
from pathlib import Path
from typing import Any, Iterable

import numpy as np

from elasticsearch_tpu.common.versioning import CURRENT_VERSION
from elasticsearch_tpu.mapping.mapper import (
    ParsedDocument, KIND_TEXT, KIND_KEYWORD, KIND_NUMERIC, KIND_VECTOR,
    KIND_MVECTOR, KIND_GEO, KIND_SHAPE)

# Process-unique block identities (itertools.count.__next__ is atomic under
# CPython): every Segment object gets one at construction. seg_id alone is
# NOT a stable identity — a recovered commit installs a DIFFERENT source
# engine's segments under potentially colliding seg_ids — so device-resident
# caches (the collective plane's per-segment block cache) key on block_uid,
# which changes exactly when the backing column arrays change.
import itertools as _itertools

_block_uids = _itertools.count(1)

# Position-slot cap per text field (docs longer than this are truncated at
# index time; reference analog: index.mapping.depth/field limits). Padded to
# a multiple of _ROW_PAD for TPU lane tiling.
DEFAULT_MAX_TOKENS = 512
_ROW_PAD = 8

# index.store.type → on-disk layout (IndexStoreModule registry; plugins
# extend it — store-smb adds the smb_* names). Layouts: "compressed"
# (npz deflate), "uncompressed" (plain npz, faster open), "npy_dir"
# (one .npy per column, OS-mmap'd on read so cold columns page lazily).
STORE_TYPES: dict[str, str] = {
    "fs": "compressed", "default": "compressed",
    "niofs": "uncompressed", "simple_fs": "uncompressed",
    "simplefs": "uncompressed",
    "mmapfs": "npy_dir", "mmap_fs": "npy_dir",
}


def validate_store_type(store_type: str) -> str:
    """→ layout name, raising the create-index-time error for unknown
    types (IndexStoreModule resolution; indices/service validates at
    creation so a typo can't produce an index that fails every flush)."""
    layout = STORE_TYPES.get(str(store_type))
    if layout is None:
        from elasticsearch_tpu.common.errors import IllegalArgumentError
        raise IllegalArgumentError(
            f"unknown index.store.type [{store_type}] "
            f"(registered: {sorted(STORE_TYPES)})")
    return layout


def _column_file(arrays_dir: Path, key: str) -> Path:
    """One encoding for column-key → filename (shared by write + mmap
    read; field names may contain characters unfit for filenames)."""
    from urllib.parse import quote
    return arrays_dir / (quote(key, safe=".") + ".npy")


class _MmapArrays:
    """Mapping view over a per-column .npy directory, each array opened
    with ``mmap_mode="r"`` — reads page in on demand (the mmapfs
    DirectoryService strategy)."""

    def __init__(self, path: Path):
        self._path = path

    def __getitem__(self, key: str) -> np.ndarray:
        f = _column_file(self._path, key)
        if not f.exists():
            raise KeyError(key)
        return np.load(f, mmap_mode="r")

    def get(self, key: str, default=None):
        try:
            return self[key]
        except KeyError:
            return default


def pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------------------------------------------------------------------------
# Impact-ordered index: quantized eager impacts + per-block maxima
# (BM25S-style impact precompute, PAPERS.md; GPUSparse's block-organized
# dense layout keeps the block tables accelerator-friendly).
# ---------------------------------------------------------------------------

#: default quantization width. uint8 keeps the per-term score error at
#: max_impact/510 (~0.2%) AND makes the df-drift requantization threshold
#: (one quantization step) wide enough that steady-state refreshes on a
#: large corpus do not requantize resident segments.
IMPACT_BITS = 8
#: rows per block-max block — MUST be a power of two so it divides the
#: pow2 doc_count_bucket row padding exactly
IMPACT_BLOCK_ROWS = 2048
#: block_max is a dense [B, V] table (GPUSparse layout); segments whose
#: table would exceed this many cells ship impacts without block maxima
#: (the eager impact lane still runs; only pruning is declined)
IMPACT_BLOCK_BUDGET = 1 << 26


@dataclass
class ImpactColumn:
    """Quantized BM25 impacts for one text field of one segment.

    ``qimp[Np, U]`` mirrors the ``uterms`` layout: slot ``(d, u)`` holds
    ``round(impact / scale)`` where ``impact = idf·tf·(k1+1)/(tf+norm)``
    — the full per-(term, doc) BM25 contribution precomputed at
    build time (BM25S), so query-time scoring is a dense compare +
    integer gather/sum with NO per-doc float math. ``block_max[B, V]``
    carries, per fixed row block, the max quantized impact of every
    term — the WAND/block-max upper-bound table — with an OCCUPANCY
    floor: present-term cells store at least 1, so a zero cell means
    the term does not occur in the block at all (the pruning lane keys
    its skip on that). Quantization error is ≤ ``scale/2`` per matched
    term (``bound_per_term``).

    idf (and avgdl) are READER-global at build time; the snapshot
    fields let later refreshes measure cross-segment df drift and
    requantize only when the drift exceeds one quantization step
    (``drift_bound`` vs ``step_rel``)."""
    qimp: np.ndarray                 # [Np, U] uint8/uint16
    block_max: np.ndarray | None     # [B, V] same dtype (None: over budget)
    scale: float                     # dequant factor: score = Σq · scale
    bits: int
    block_rows: int
    doc_count: int                   # idf snapshot: reader doc count
    avgdl: float                     # idf snapshot: reader avgdl
    k1: float
    b: float
    quant_gen: int = 0               # bumped on requantization

    @property
    def step_rel(self) -> float:
        """One quantization step as a fraction of the max impact."""
        return 1.0 / ((1 << self.bits) - 1)

    @property
    def bound_per_term(self) -> float:
        """Score-units error bound per matched query term (quantization
        half-step plus the tolerated idf drift of one full step)."""
        return self.scale * 0.5 + \
            self.scale * ((1 << self.bits) - 1) * self.step_rel

    def drift_bound(self, doc_count: int, avgdl: float) -> float:
        """Conservative SCORE-UNITS bound on the impact drift since the
        snapshot: ``2·|ln(N/N₀)|`` bounds any term's idf movement (df
        can drift by at most the added/removed docs), ``|ln(a/a₀)|``
        the length-norm movement, and ``k1+1`` bounds tfNorm — the
        product bounds how far a precomputed impact can sit from its
        current-statistics value. Compared against one quantization
        step (``scale``) by the requant policy: drift within a step is
        inside the documented ``bound_per_term`` envelope."""
        import math
        n0 = max(self.doc_count, 1)
        a0 = max(self.avgdl, 1e-9)
        # |ln(N/N₀)| bounds idf movement at FIXED df (d idf/dN = 1/(N+1));
        # a rare term whose df itself jumps inside the growth window can
        # exceed this between requants — that residual is part of the
        # documented bound_per_term envelope (see ROOFLINE.md), and the
        # corpus-growth trigger caps how long it can accumulate.
        rel = abs(math.log(max(doc_count, 1) / n0)) + \
            abs(math.log(max(avgdl, 1e-9) / a0))
        return (self.k1 + 1.0) * rel


def build_impact_column(col: TextFieldColumn, *, df: np.ndarray,
                        doc_count: int, avgdl: float,
                        k1: float = 1.2, b: float = 0.75,
                        bits: int = IMPACT_BITS,
                        block_rows: int = IMPACT_BLOCK_ROWS,
                        block_budget: int = IMPACT_BLOCK_BUDGET,
                        quant_gen: int = 0) -> ImpactColumn:
    """Precompute one segment's quantized impact column + block maxima.

    ``df`` is the [V] READER-global doc frequency of this segment's
    terms (positional by term id) — the idf snapshot baked into the
    impacts; ``doc_count``/``avgdl`` are the matching reader-global
    statistics. Pure numpy, O(N·U): cheap enough that the PR 5
    incremental data plane pays it once per NEW segment per refresh."""
    if bits not in (8, 16):
        raise ValueError(f"impact bits must be 8 or 16, got {bits}")
    if block_rows & (block_rows - 1):
        raise ValueError("impact block_rows must be a power of two")
    dtype = np.uint8 if bits == 8 else np.uint16
    qmax = (1 << bits) - 1
    np_docs, _u = col.uterms.shape
    v = int(np.asarray(df).shape[0])
    n0 = max(int(doc_count), 1)
    dfv = np.asarray(df, np.float64)
    idf = np.log1p((n0 - dfv + 0.5) / (dfv + 0.5))
    idf = np.where(dfv > 0, np.maximum(idf, 0.0), 0.0)
    norm = k1 * (1.0 - b + b * np.asarray(col.doc_len, np.float64)
                 / max(float(avgdl), 1e-9))
    utf = np.asarray(col.utf, np.float64)
    valid = np.asarray(col.uterms) >= 0
    tfn = np.divide(utf * (k1 + 1.0), utf + norm[:, None],
                    out=np.zeros_like(utf), where=valid)
    imp = np.where(valid, idf[np.maximum(col.uterms, 0)] * tfn, 0.0)
    mx = float(imp.max()) if imp.size else 0.0
    scale = (mx / qmax) if mx > 0 else 1.0
    qimp = np.clip(np.rint(imp / scale), 0, qmax).astype(dtype)
    r = min(block_rows, np_docs)
    n_blocks = max(np_docs // max(r, 1), 1)
    block_max: np.ndarray | None
    if n_blocks * v > block_budget:
        block_max = None
    else:
        block_max = np.zeros((n_blocks, max(v, 1)), dtype)
        ut = np.asarray(col.uterms)
        for bi in range(n_blocks):
            sl = slice(bi * r, (bi + 1) * r)
            rows_t = ut[sl][valid[sl]]
            # occupancy floor: a PRESENT (block, term) cell stores
            # max(q, 1) so zero means "term absent from block" — a
            # low-idf term whose impacts all quantize to 0 must still
            # keep its blocks sweepable (the eager lane counts such
            # docs as hits at score 0; the pruned lane has to agree).
            # Still a valid upper bound: 1 ≥ 0 and bounds only need ≥.
            rows_q = np.maximum(qimp[sl][valid[sl]], 1)
            np.maximum.at(block_max[bi], rows_t, rows_q)
    return ImpactColumn(qimp=qimp, block_max=block_max, scale=scale,
                        bits=bits, block_rows=r, doc_count=n0,
                        avgdl=float(avgdl), k1=float(k1), b=float(b),
                        quant_gen=quant_gen)


def doc_count_bucket(n: int) -> int:
    """Bucketized row padding: bounds the number of distinct compiled shapes
    as segments grow (SURVEY.md §7 'Incrementality'). Geometric buckets:
    128, 256, 512, ... so at most ~2x memory overhead and O(log N) shapes."""
    b = 128
    while b < n:
        b *= 2
    return b


@dataclass
class TextFieldColumn:
    """Device-layout columns for one analyzed text field of one segment."""
    terms: list[str]                 # tid → term (sorted; per-segment dict)
    tokens: np.ndarray               # [Np, L] int32, -1 pad (positional view)
    uterms: np.ndarray               # [Np, U] int32, -1 pad (scoring view)
    utf: np.ndarray                  # [Np, U] float32
    doc_len: np.ndarray              # [Np] int32 (token count incl. truncation)
    df: np.ndarray                   # [V] int32 docs-containing-term
    total_tokens: int                # Σ doc_len over real docs (for avgdl)
    # False when positions were not indexed (the reference's
    # index_options: freqs): tokens is a -1 stub and positional queries
    # (match_phrase, span_near) refuse the field instead of silently
    # matching nothing
    has_positions: bool = True
    term_index: dict[str, int] = dc_field(default_factory=dict)

    def __post_init__(self):
        if not self.term_index:
            self.term_index = {t: i for i, t in enumerate(self.terms)}

    def tid(self, term: str) -> int:
        """Query-time term lookup; -1 = term absent from this segment."""
        return self.term_index.get(term, -1)

    def ctf(self, tid: int) -> float:
        """Collection term frequency (Σ tf over docs) for one term id.
        The per-term vector is built in ONE pass over the column on first
        use and cached — per-term full-matrix reductions at DFS time cost
        ~3 s/batch at 1M docs before this cache."""
        vec = getattr(self, "_ctf_vec", None)
        if vec is None:
            vec = np.zeros(self.df.shape[0], np.float64)
            valid = self.uterms >= 0
            np.add.at(vec, self.uterms[valid], self.utf[valid])
            object.__setattr__(self, "_ctf_vec", vec)
        return float(vec[tid]) if 0 <= tid < vec.shape[0] else 0.0


@dataclass
class KeywordFieldColumn:
    vocab: list[str]                 # sorted: ordinal order == lexical order
    ords: np.ndarray                 # [Np, K] int32, -1 pad
    index: dict[str, int] = dc_field(default_factory=dict)

    def __post_init__(self):
        if not self.index:
            self.index = {v: i for i, v in enumerate(self.vocab)}

    def ord(self, value: str) -> int:
        return self.index.get(value, -1)


@dataclass
class NumericFieldColumn:
    values: np.ndarray               # [Np] float64
    exists: np.ndarray               # [Np] bool


@dataclass
class VectorFieldColumn:
    vecs: np.ndarray                 # [Np, D] float32
    exists: np.ndarray               # [Np] bool
    dims: int


@dataclass
class MultiVectorFieldColumn:
    """``rank_vectors`` doc values: per-doc [T, D] token matrices padded
    to the column-wide pow2 token bucket (like the uterms layout), for
    late-interaction MaxSim scoring (ops/maxsim.py). ``lens`` marks the
    real token rows; padding rows are zero."""
    vecs: np.ndarray                 # [Np, T, D] float32
    lens: np.ndarray                 # [Np] int32 real token rows
    exists: np.ndarray               # [Np] bool
    dims: int


@dataclass
class QuantizedVectorColumn:
    """int8 scalar quantization of one segment's vector column
    (`index.knn.quantization: int8`): ``v ≈ q·scale + offset`` per
    component, with the scale/offset SNAPSHOT taken over the segment's
    own value range at quantization time — segments are immutable, so
    unlike the impact columns (reader-global idf snapshots) the
    snapshot never drifts and never requantizes. Per-component error is
    ≤ ``scale/2``; a query's score error is bounded by
    ``scale/2 · Σ|q_i|`` (the stamped quantization bound the recall
    tests assert against)."""
    qvecs: np.ndarray                # [Np, D] or [Np, T, D] int8
    scale: float
    offset: float
    dims: int

    def score_bound(self, qn: np.ndarray) -> float:
        """Score-units error bound for one (normalized) query vector:
        per-component quantization error ≤ scale/2, accumulated over
        the |q|-weighted sum — for MaxSim, per QUERY TOKEN (the max
        over doc tokens moves by at most the per-token bound)."""
        q = np.abs(np.asarray(qn, np.float64))
        if q.ndim == 1:
            return float(self.scale * 0.5 * q.sum())
        return float(self.scale * 0.5 * q.sum(axis=-1).sum())


def quantize_vectors(vecs: np.ndarray, dims: int) -> QuantizedVectorColumn:
    """Asymmetric int8 scalar quantization over one segment's (already
    L2-normalized) vector values: offset centers the range, scale maps
    it onto [-127, 127]. Pure numpy; paid once per NEW segment (the
    host column caches on the immutable Segment, PR 5 discipline)."""
    v = np.asarray(vecs, np.float32)
    if v.size:
        mn, mx = float(v.min()), float(v.max())
    else:
        mn = mx = 0.0
    offset = np.float32((mx + mn) / 2.0)
    half = max(mx - float(offset), float(offset) - mn)
    scale = np.float32(half / 127.0) if half > 0 else np.float32(1.0)
    q = np.clip(np.rint((v - offset) / scale), -127, 127).astype(np.int8)
    return QuantizedVectorColumn(qvecs=q, scale=float(scale),
                                 offset=float(offset), dims=dims)


@dataclass
class GeoFieldColumn:
    lat: np.ndarray                  # [Np] float64
    lon: np.ndarray                  # [Np] float64
    exists: np.ndarray               # [Np] bool


@dataclass
class ShapeFieldColumn:
    """geo_shape doc values: each doc's shape as concatenated vertex
    RINGS (built by utils/geoshape.parse_shape_rings — polygon outer +
    hole rings, multipolygon members, line runs, degenerate point
    rings), padded to the column-wide max. ``rid`` gates edges to
    same-ring neighbours and ``area`` marks rings that enclose area
    (even-odd parity ignores line runs). Relations run as dense
    multi-ring tests on device (ops/geoshape.py) — the TPU-native
    replacement for the reference's geohash prefix-tree index
    (core/index/mapper/geo/GeoShapeFieldMapper.java)."""
    lats: np.ndarray                 # [Np, V] float32
    lons: np.ndarray                 # [Np, V] float32
    nv: np.ndarray                   # [Np] int32 edge slots (verts - 1)
    exists: np.ndarray               # [Np] bool
    rid: np.ndarray | None = None    # [Np, V] int32 ring id (-1 pad)
    area: np.ndarray | None = None   # [Np, V] bool

    def __post_init__(self):
        if self.rid is None:
            # legacy single-ring columns: one ring over the nv window
            self.rid = np.where(
                np.arange(self.lats.shape[1])[None, :] <=
                self.nv[:, None], 0, -1).astype(np.int32)
            self.rid[~self.exists] = -1
        if self.area is None:
            self.area = self.rid >= 0


@dataclass
class NestedBlock:
    """One nested path's child rows for a segment: a full child segment
    (nested objects are docs of their own — ref: ObjectMapper Nested,
    nested objects index as adjacent hidden Lucene docs) plus the
    child-row → parent-row join column."""
    segment: "Segment"
    parent: np.ndarray               # [child padded] int32, -1 pad


@dataclass
class Segment:
    seg_id: int
    num_docs: int                    # true doc count (rows beyond are pad)
    padded_docs: int
    ids: list[str]                   # local doc → _id
    sources: list[dict]              # stored fields (_source)
    text_fields: dict[str, TextFieldColumn]
    keyword_fields: dict[str, KeywordFieldColumn]
    numeric_fields: dict[str, NumericFieldColumn]
    vector_fields: dict[str, VectorFieldColumn]
    geo_fields: dict[str, GeoFieldColumn]
    version_id: int = CURRENT_VERSION.id
    # False for bulk-ingested segments built without stored _source: their
    # docs cannot be re-analyzed, so background/force merges must keep the
    # segment as-is instead of re-parsing it (engine.force_merge honors
    # this; Lucene's addIndexes'd segments merge at the codec level and
    # have no such constraint — columnar re-analysis here does).
    source_complete: bool = True
    # nested path → child block (mapping "type": "nested")
    nested_blocks: dict[str, NestedBlock] = dc_field(default_factory=dict)
    # rank_vectors columns (multi-vector late interaction)
    mvector_fields: dict[str, MultiVectorFieldColumn] = dc_field(
        default_factory=dict)
    # geo_shape columns (vertex rings, ShapeFieldColumn)
    shape_fields: dict[str, ShapeFieldColumn] = dc_field(
        default_factory=dict)
    # stable block identity across reader swaps: a SearcherView snapshot
    # holds the same Segment OBJECTS across refresh generations, so a
    # device-block cache keyed on block_uid reuses resident columns while
    # any newly built/merged/recovered segment (a new object) re-uploads
    block_uid: int = dc_field(default_factory=lambda: next(_block_uids))

    def memory_bytes(self) -> int:
        total = 0
        for col in self.text_fields.values():
            total += col.tokens.nbytes
            total += col.uterms.nbytes + col.utf.nbytes + col.doc_len.nbytes
            total += col.df.nbytes
        for col in self.keyword_fields.values():
            total += col.ords.nbytes
        for col in self.numeric_fields.values():
            total += col.values.nbytes + col.exists.nbytes
        for col in self.vector_fields.values():
            total += col.vecs.nbytes
        for col in self.mvector_fields.values():
            total += col.vecs.nbytes + col.lens.nbytes
        for col in self.geo_fields.values():
            total += col.lat.nbytes + col.lon.nbytes
        for col in self.shape_fields.values():
            total += col.lats.nbytes + col.lons.nbytes + col.nv.nbytes \
                + col.rid.nbytes + col.area.nbytes
        for blk in self.nested_blocks.values():
            total += blk.segment.memory_bytes() + blk.parent.nbytes
        return total

    # ---- bulk columnar ingest ---------------------------------------------

    @staticmethod
    def from_packed_text(seg_id: int, field: str, *, terms: list[str],
                         tokens: np.ndarray | None, uterms: np.ndarray,
                         utf: np.ndarray, doc_len: np.ndarray,
                         df: np.ndarray, num_docs: int,
                         total_tokens: int | None = None,
                         ids: list[str] | None = None,
                         sources: list[dict] | None = None) -> "Segment":
        """Construct an immutable single-text-field segment directly from
        pre-tokenized packed columns — the high-throughput bulk-load path,
        the analog of Lucene's ``IndexWriter.addIndexes(CodecReader...)``
        (segment-level ingest without re-analysis). Bulk loaders and the
        benchmark corpus builder use this; the per-document path is
        :class:`SegmentBuilder`.

        Invariants (the SegmentBuilder contract): ``terms`` is SORTED and
        term ids are ranks in it; ``tokens`` is position-indexed with -1
        holes — or ``None`` to skip position indexing entirely (the
        reference's ``index_options: freqs``: ~40% less memory, positional
        queries rejected); rows at and beyond ``num_docs`` are padding.
        """
        np_docs = int(uterms.shape[0])
        has_positions = tokens is not None
        if tokens is None:
            tokens = np.full((np_docs, 8), -1, np.int32)
        if not (tokens.shape[0] == np_docs == doc_len.shape[0]
                == utf.shape[0]):
            raise ValueError("packed columns disagree on row count")
        if num_docs > np_docs:
            raise ValueError(f"num_docs {num_docs} > padded rows {np_docs}")
        if total_tokens is None:
            total_tokens = int(np.asarray(doc_len[:num_docs]).sum())
        col = TextFieldColumn(
            terms=list(terms),
            tokens=np.ascontiguousarray(tokens, dtype=np.int32),
            uterms=np.ascontiguousarray(uterms, dtype=np.int32),
            utf=np.ascontiguousarray(utf, dtype=np.float32),
            doc_len=np.ascontiguousarray(doc_len, dtype=np.int32),
            df=np.ascontiguousarray(df, dtype=np.int32),
            total_tokens=total_tokens, has_positions=has_positions)
        if ids is None:
            ids = [str(i) for i in range(num_docs)] + \
                [""] * (np_docs - num_docs)
        source_complete = sources is not None
        if sources is None:
            sources = [{}] * np_docs       # shared empty dict: read-only
        return Segment(seg_id=seg_id, num_docs=num_docs, padded_docs=np_docs,
                       ids=ids, sources=sources, text_fields={field: col},
                       keyword_fields={}, numeric_fields={},
                       vector_fields={}, geo_fields={},
                       source_complete=source_complete)

    # ---- persistence ------------------------------------------------------

    def write(self, path: Path, store_type: str = "fs") -> None:
        """Persist as npz + json (write-tmp-then-rename like the reference's
        MetaDataStateFormat, core/gateway/MetaDataStateFormat.java).

        ``store_type`` is the `index.store.type` seam (core/index/store/
        IndexStoreModule — fs/niofs/mmapfs/default; plugins add more,
        store-smb): "fs"/"default" = compressed npz; "niofs"/"simple_fs"
        = uncompressed npz (faster open, eager read); "mmapfs"/
        "mmap_fs" = one .npy per column, opened with OS mmap so cold
        columns page in on demand (the FsDirectoryService mmap
        strategy). Unknown types raise."""
        layout = validate_store_type(store_type)
        path.mkdir(parents=True, exist_ok=True)
        arrays: dict[str, np.ndarray] = {}
        meta: dict[str, Any] = {
            "seg_id": self.seg_id, "num_docs": self.num_docs,
            "padded_docs": self.padded_docs, "version_id": self.version_id,
            "source_complete": self.source_complete,
            "text_fields": {}, "keyword_fields": {}, "numeric_fields": [],
            "vector_fields": {}, "geo_fields": [],
        }
        for name, c in self.text_fields.items():
            meta["text_fields"][name] = {"terms": c.terms,
                                         "total_tokens": c.total_tokens,
                                         "has_positions": c.has_positions}
            for a in ("tokens", "uterms", "utf", "doc_len", "df"):
                arrays[f"t.{name}.{a}"] = getattr(c, a)
        for name, c in self.keyword_fields.items():
            meta["keyword_fields"][name] = {"vocab": c.vocab}
            arrays[f"k.{name}.ords"] = c.ords
        for name, c in self.numeric_fields.items():
            meta["numeric_fields"].append(name)
            arrays[f"n.{name}.values"] = c.values
            arrays[f"n.{name}.exists"] = c.exists
        for name, c in self.vector_fields.items():
            meta["vector_fields"][name] = {"dims": c.dims}
            arrays[f"v.{name}.vecs"] = c.vecs
            arrays[f"v.{name}.exists"] = c.exists
        meta["mvector_fields"] = {name: {"dims": c.dims}
                                  for name, c in self.mvector_fields.items()}
        for name, c in self.mvector_fields.items():
            arrays[f"mv.{name}.vecs"] = c.vecs
            arrays[f"mv.{name}.lens"] = c.lens
            arrays[f"mv.{name}.exists"] = c.exists
        for name, c in self.geo_fields.items():
            meta["geo_fields"].append(name)
            arrays[f"g.{name}.lat"] = c.lat
            arrays[f"g.{name}.lon"] = c.lon
            arrays[f"g.{name}.exists"] = c.exists
        meta["shape_fields"] = sorted(self.shape_fields)
        for name, c in self.shape_fields.items():
            arrays[f"s.{name}.lats"] = c.lats
            arrays[f"s.{name}.lons"] = c.lons
            arrays[f"s.{name}.nv"] = c.nv
            arrays[f"s.{name}.exists"] = c.exists
            arrays[f"s.{name}.rid"] = c.rid
            arrays[f"s.{name}.area"] = c.area

        meta["nested"] = sorted(self.nested_blocks)
        for p, blk in self.nested_blocks.items():
            blk.segment.write(path / f"nested_{p}", store_type=store_type)
            arrays[f"x.{p}.parent"] = blk.parent
        meta["store"] = layout

        import shutil
        tmp_meta, tmp_src = (path / "meta.json.tmp",
                             path / "source.jsonl.tmp")
        if layout == "npy_dir":
            tmp_dir = path / "arrays.tmp"
            if tmp_dir.exists():
                shutil.rmtree(tmp_dir)
            tmp_dir.mkdir()
            for key, arr in arrays.items():
                np.save(_column_file(tmp_dir, key),
                        np.ascontiguousarray(arr))
            final_dir = path / "arrays"
            if final_dir.exists():
                shutil.rmtree(final_dir)
            tmp_dir.rename(final_dir)
            # a crash-interrupted earlier write under another store type
            # may have left the other layout's artifact — remove it, or
            # file_manifest() ships the dead file to replicas/snapshots
            (path / "arrays.npz").unlink(missing_ok=True)
        else:
            tmp_npz = path / "arrays.npz.tmp"
            with open(tmp_npz, "wb") as f:
                if layout == "uncompressed":
                    np.savez(f, **arrays)
                else:
                    np.savez_compressed(f, **arrays)
            tmp_npz.rename(path / "arrays.npz")
            if (path / "arrays").exists():
                shutil.rmtree(path / "arrays")
        tmp_meta.write_text(json.dumps(meta))
        with open(tmp_src, "w") as f:
            for doc_id, src in zip(self.ids, self.sources):
                f.write(json.dumps({"_id": doc_id, "_source": src}) + "\n")
        # meta.json is the "segment fully persisted" sentinel (Engine.flush
        # checks it) — rename it LAST so a crash between renames can never
        # produce a sentinel-present-but-incomplete segment.
        tmp_src.rename(path / "source.jsonl")
        tmp_meta.rename(path / "meta.json")

    @staticmethod
    def read(path: Path) -> "Segment":
        meta = json.loads((path / "meta.json").read_text())
        if meta.get("store") == "npy_dir":
            arrays = _MmapArrays(path / "arrays")
        else:
            arrays = np.load(path / "arrays.npz")
        ids, sources = [], []
        with open(path / "source.jsonl") as f:
            for line in f:
                rec = json.loads(line)
                ids.append(rec["_id"])
                sources.append(rec["_source"])
        text_fields = {
            name: TextFieldColumn(
                terms=info["terms"], total_tokens=info["total_tokens"],
                has_positions=info.get("has_positions", True),
                tokens=arrays[f"t.{name}.tokens"],
                uterms=arrays[f"t.{name}.uterms"], utf=arrays[f"t.{name}.utf"],
                doc_len=arrays[f"t.{name}.doc_len"], df=arrays[f"t.{name}.df"])
            for name, info in meta["text_fields"].items()}
        keyword_fields = {
            name: KeywordFieldColumn(vocab=info["vocab"],
                                     ords=arrays[f"k.{name}.ords"])
            for name, info in meta["keyword_fields"].items()}
        numeric_fields = {
            name: NumericFieldColumn(values=arrays[f"n.{name}.values"],
                                     exists=arrays[f"n.{name}.exists"])
            for name in meta["numeric_fields"]}
        vector_fields = {
            name: VectorFieldColumn(vecs=arrays[f"v.{name}.vecs"],
                                    exists=arrays[f"v.{name}.exists"],
                                    dims=info["dims"])
            for name, info in meta["vector_fields"].items()}
        mvector_fields = {
            name: MultiVectorFieldColumn(
                vecs=arrays[f"mv.{name}.vecs"],
                lens=arrays[f"mv.{name}.lens"],
                exists=arrays[f"mv.{name}.exists"], dims=info["dims"])
            for name, info in meta.get("mvector_fields", {}).items()}
        geo_fields = {
            name: GeoFieldColumn(lat=arrays[f"g.{name}.lat"],
                                 lon=arrays[f"g.{name}.lon"],
                                 exists=arrays[f"g.{name}.exists"])
            for name in meta["geo_fields"]}
        shape_fields = {
            name: ShapeFieldColumn(
                lats=arrays[f"s.{name}.lats"],
                lons=arrays[f"s.{name}.lons"],
                nv=arrays[f"s.{name}.nv"],
                exists=arrays[f"s.{name}.exists"],
                # pre-round-5 stores lack ring ids; __post_init__
                # derives the legacy single-ring layout
                rid=arrays.get(f"s.{name}.rid"),
                area=arrays.get(f"s.{name}.area"))
            for name in meta.get("shape_fields", [])}
        nested_blocks = {
            p: NestedBlock(segment=Segment.read(path / f"nested_{p}"),
                           parent=arrays[f"x.{p}.parent"])
            for p in meta.get("nested", [])}
        return Segment(seg_id=meta["seg_id"], num_docs=meta["num_docs"],
                       padded_docs=meta["padded_docs"], ids=ids, sources=sources,
                       text_fields=text_fields, keyword_fields=keyword_fields,
                       numeric_fields=numeric_fields, vector_fields=vector_fields,
                       geo_fields=geo_fields, version_id=meta["version_id"],
                       source_complete=meta.get("source_complete", True),
                       nested_blocks=nested_blocks,
                       shape_fields=shape_fields,
                       mvector_fields=mvector_fields)


class SegmentBuilder:
    """Accumulates parsed documents, emits an immutable :class:`Segment`.

    The in-memory analog of Lucene's DocumentsWriter per-thread buffers; a
    refresh (core/index/engine/InternalEngine.java:558) turns the buffer into
    a segment and swaps the reader.
    """

    def __init__(self, seg_id: int, max_tokens: int = DEFAULT_MAX_TOKENS):
        self.seg_id = seg_id
        self.max_tokens = max_tokens
        self.docs: list[ParsedDocument] = []

    def add(self, doc: ParsedDocument) -> int:
        """→ local doc number."""
        self.docs.append(doc)
        return len(self.docs) - 1

    def __len__(self) -> int:
        return len(self.docs)

    @property
    def num_docs(self) -> int:
        return len(self.docs)

    def build(self) -> Segment:
        n = len(self.docs)
        np_docs = doc_count_bucket(max(n, 1))
        field_kinds: dict[str, str] = {}
        for d in self.docs:
            for fname, pf in d.fields.items():
                field_kinds.setdefault(fname, pf.kind)

        text_fields: dict[str, TextFieldColumn] = {}
        keyword_fields: dict[str, KeywordFieldColumn] = {}
        numeric_fields: dict[str, NumericFieldColumn] = {}
        vector_fields: dict[str, VectorFieldColumn] = {}
        mvector_fields: dict[str, MultiVectorFieldColumn] = {}
        geo_fields: dict[str, GeoFieldColumn] = {}
        shape_fields: dict[str, ShapeFieldColumn] = {}

        for fname, kind in field_kinds.items():
            if kind == KIND_TEXT:
                text_fields[fname] = self._build_text(fname, n, np_docs)
            elif kind == KIND_KEYWORD:
                keyword_fields[fname] = self._build_keyword(fname, n, np_docs)
            elif kind == KIND_NUMERIC:
                numeric_fields[fname] = self._build_numeric(fname, n, np_docs)
            elif kind == KIND_VECTOR:
                vector_fields[fname] = self._build_vector(fname, n, np_docs)
            elif kind == KIND_MVECTOR:
                mvector_fields[fname] = self._build_mvector(fname, n,
                                                            np_docs)
            elif kind == KIND_GEO:
                geo_fields[fname] = self._build_geo(fname, n, np_docs)
            elif kind == KIND_SHAPE:
                shape_fields[fname] = self._build_shape(fname, n, np_docs)

        return Segment(
            seg_id=self.seg_id, num_docs=n, padded_docs=np_docs,
            ids=[d.doc_id for d in self.docs],
            sources=[d.source for d in self.docs],
            text_fields=text_fields, keyword_fields=keyword_fields,
            numeric_fields=numeric_fields, vector_fields=vector_fields,
            geo_fields=geo_fields, shape_fields=shape_fields,
            mvector_fields=mvector_fields,
            nested_blocks=self._build_nested())

    def _build_nested(self) -> dict[str, NestedBlock]:
        """Each nested path's objects become rows of a CHILD segment built
        through the ordinary per-kind builders, plus a parent join column."""
        paths: set[str] = set()
        for d in self.docs:
            paths.update(d.nested)
        blocks: dict[str, NestedBlock] = {}
        for path in sorted(paths):
            child = SegmentBuilder(seg_id=0, max_tokens=self.max_tokens)
            parents: list[int] = []
            for i, d in enumerate(self.docs):
                for row in d.nested.get(path, []):
                    child.docs.append(ParsedDocument(
                        doc_id="", source={}, fields=row))
                    parents.append(i)
            child_seg = child.build()
            parent = np.full(child_seg.padded_docs, -1, np.int32)
            parent[:len(parents)] = parents
            blocks[path] = NestedBlock(segment=child_seg, parent=parent)
        return blocks

    # ---- per-kind builders ------------------------------------------------

    def _field(self, doc: ParsedDocument, fname: str):
        return doc.fields.get(fname)

    def _build_text(self, fname: str, n: int, np_docs: int) -> TextFieldColumn:
        # First pass: vocabulary over the segment. Token positions beyond
        # max_tokens are truncated (position-indexed layout: slot == position).
        vocab: dict[str, int] = {}
        doc_tokens: list[list[tuple[int, int]]] = []  # per doc: (tid, position)
        max_pos = 0
        max_unique = 0
        total_tokens = 0
        for d in self.docs:
            pf = self._field(d, fname)
            pairs = []
            if pf is not None:
                for t in pf.tokens:
                    if t.position >= self.max_tokens:
                        break
                    tid = vocab.setdefault(t.term, len(vocab))
                    pairs.append((tid, t.position))
            doc_tokens.append(pairs)
            if pairs:
                max_pos = max(max_pos, pairs[-1][1] + 1)
            max_unique = max(max_unique, len({tid for tid, _ in pairs}))
            total_tokens += len(pairs)

        terms = sorted(vocab)  # sorted dictionary; remap ids to sorted order
        remap = np.empty(max(len(vocab), 1), dtype=np.int32)
        for new_id, term in enumerate(terms):
            remap[vocab[term]] = new_id

        L = pad_to(max(max_pos, 1), _ROW_PAD)
        U = pad_to(max(max_unique, 1), _ROW_PAD)
        tokens = np.full((np_docs, L), -1, dtype=np.int32)
        uterms = np.full((np_docs, U), -1, dtype=np.int32)
        utf = np.zeros((np_docs, U), dtype=np.float32)
        doc_len = np.zeros(np_docs, dtype=np.int32)
        df = np.zeros(max(len(vocab), 1), dtype=np.int32)

        for i, pairs in enumerate(doc_tokens):
            counts: dict[int, int] = {}
            for tid, pos in pairs:
                tid = int(remap[tid])
                if tokens[i, pos] == -1:
                    # slot == position; first token wins when an analyzer
                    # emits several terms at one position (shingles/synonyms)
                    # — those extra terms still score via uterms/utf, they
                    # just don't participate in positional (phrase) matching
                    tokens[i, pos] = tid
                counts[tid] = counts.get(tid, 0) + 1
            for u, (tid, tf) in enumerate(sorted(counts.items())):
                uterms[i, u] = tid
                utf[i, u] = tf
                df[tid] += 1
            doc_len[i] = len(pairs)

        return TextFieldColumn(terms=terms, tokens=tokens,
                               uterms=uterms, utf=utf, doc_len=doc_len, df=df,
                               total_tokens=total_tokens)

    def _build_keyword(self, fname: str, n: int, np_docs: int) -> KeywordFieldColumn:
        values: set[str] = set()
        per_doc: list[list[str]] = []
        kmax = 1
        for d in self.docs:
            pf = self._field(d, fname)
            kws = pf.keywords if pf else []
            per_doc.append(kws)
            values.update(kws)
            kmax = max(kmax, len(kws))
        vocab = sorted(values)
        index = {v: i for i, v in enumerate(vocab)}
        ords = np.full((np_docs, kmax), -1, dtype=np.int32)
        for i, kws in enumerate(per_doc):
            for j, v in enumerate(kws):
                ords[i, j] = index[v]
        return KeywordFieldColumn(vocab=vocab, ords=ords, index=index)

    def _build_numeric(self, fname: str, n: int, np_docs: int) -> NumericFieldColumn:
        values = np.zeros(np_docs, dtype=np.float64)
        exists = np.zeros(np_docs, dtype=bool)
        for i, d in enumerate(self.docs):
            pf = self._field(d, fname)
            if pf and pf.numerics:
                values[i] = pf.numerics[0]
                exists[i] = True
        return NumericFieldColumn(values=values, exists=exists)

    def _build_vector(self, fname: str, n: int, np_docs: int) -> VectorFieldColumn:
        dims = 0
        for d in self.docs:
            pf = self._field(d, fname)
            if pf is not None and pf.vector is not None:
                dims = int(pf.vector.shape[0])
                break
        vecs = np.zeros((np_docs, max(dims, 1)), dtype=np.float32)
        exists = np.zeros(np_docs, dtype=bool)
        for i, d in enumerate(self.docs):
            pf = self._field(d, fname)
            if pf is not None and pf.vector is not None:
                vecs[i] = pf.vector
                exists[i] = True
        return VectorFieldColumn(vecs=vecs, exists=exists, dims=dims)

    def _build_mvector(self, fname: str, n: int,
                       np_docs: int) -> MultiVectorFieldColumn:
        dims = 0
        tmax = 1
        for d in self.docs:
            pf = self._field(d, fname)
            if pf is not None and pf.mvector is not None:
                dims = int(pf.mvector.shape[1])
                tmax = max(tmax, int(pf.mvector.shape[0]))
        # pow2 token bucket (like uterms' _ROW_PAD padding) so segments
        # with similar token counts share compiled MaxSim shapes
        t_pad = 1
        while t_pad < tmax:
            t_pad *= 2
        vecs = np.zeros((np_docs, t_pad, max(dims, 1)), np.float32)
        lens = np.zeros(np_docs, np.int32)
        exists = np.zeros(np_docs, bool)
        for i, d in enumerate(self.docs):
            pf = self._field(d, fname)
            if pf is not None and pf.mvector is not None:
                t = pf.mvector.shape[0]
                vecs[i, :t] = pf.mvector
                lens[i] = t
                exists[i] = True
        return MultiVectorFieldColumn(vecs=vecs, lens=lens, exists=exists,
                                      dims=dims)

    def _build_geo(self, fname: str, n: int, np_docs: int) -> GeoFieldColumn:
        lat = np.zeros(np_docs, dtype=np.float64)
        lon = np.zeros(np_docs, dtype=np.float64)
        exists = np.zeros(np_docs, dtype=bool)
        for i, d in enumerate(self.docs):
            pf = self._field(d, fname)
            if pf is not None and pf.geo is not None:
                lat[i], lon[i] = pf.geo
                exists[i] = True
        return GeoFieldColumn(lat=lat, lon=lon, exists=exists)

    def _build_shape(self, fname: str, n: int,
                     np_docs: int) -> ShapeFieldColumn:
        rings = []
        vmax = 2
        for d in self.docs:
            pf = self._field(d, fname)
            ring = pf.shape if pf is not None else None
            rings.append(ring)
            if ring is not None:
                vmax = max(vmax, len(ring[0]))
        lats = np.zeros((np_docs, vmax), np.float32)
        lons = np.zeros((np_docs, vmax), np.float32)
        rid = np.full((np_docs, vmax), -1, np.int32)
        area = np.zeros((np_docs, vmax), bool)
        nv = np.zeros(np_docs, np.int32)
        exists = np.zeros(np_docs, bool)
        for i, ring in enumerate(rings):
            if ring is None:
                continue
            rl, ro, rr, ra = ring
            lats[i, :len(rl)] = rl
            lons[i, :len(ro)] = ro
            rid[i, :len(rr)] = rr
            area[i, :len(ra)] = ra
            nv[i] = len(rl) - 1
            exists[i] = True
        return ShapeFieldColumn(lats=lats, lons=lons, nv=nv,
                                exists=exists, rid=rid, area=area)


def row_meta(seg: "Segment", local: int) -> dict:
    """Metadata-field values of one row out of a segment's reserved
    columns (_type/_parent/_routing keyword, _timestamp/_ttl/_version
    numeric) — what the internal field mappers materialized at index
    time."""
    out: dict = {}
    for key in ("_type", "_parent", "_routing"):
        col = seg.keyword_fields.get(key)
        if col is not None and local < col.ords.shape[0]:
            o = int(col.ords[local, 0])
            if o >= 0:
                out[key] = col.vocab[o]
    for key in ("_timestamp", "_ttl", "_version"):
        col = seg.numeric_fields.get(key)
        if col is not None and local < col.values.shape[0] \
                and bool(col.exists[local]):
            out[key] = int(col.values[local])
    return out


def merge_segments(seg_id: int, segments: Iterable[Segment],
                   live_masks: Iterable[np.ndarray] | None = None,
                   mapper=None,
                   max_tokens: int = DEFAULT_MAX_TOKENS) -> "SegmentBuilder":
    """Background-merge equivalent (ElasticsearchConcurrentMergeScheduler):
    re-parse surviving docs into a fresh builder. Requires the mapper to
    re-analyze; engine calls this with its DocumentMapper. Each row's
    metadata columns ride through the merge (Lucene merges carry every
    stored field) — dropping them would silently break _type filters,
    parent/child joins, routed fetches, TTL sweeps and point-in-time
    _version reads for merged docs."""
    builder = SegmentBuilder(seg_id, max_tokens=max_tokens)
    masks = list(live_masks) if live_masks is not None else None
    for si, seg in enumerate(segments):
        for local in range(seg.num_docs):
            if masks is not None and not masks[si][local]:
                continue
            meta = row_meta(seg, local)
            doc = mapper.parse(seg.ids[local], seg.sources[local],
                               routing=meta.get("_routing"),
                               meta=meta or None)
            builder.add(doc)
    return builder
