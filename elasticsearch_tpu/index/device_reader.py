"""DeviceReader — an engine reader view packed into device (HBM) arrays.

The analog of acquiring an NRT searcher (IndexShard.acquireSearcher,
core/index/shard/IndexShard.java:707): an immutable point-in-time set of
segments, resident on the accelerator. Columns are uploaded once per refresh
generation and cached; queries then run entirely on-device until the final
top-k docs come back for fetch.

Also aggregates per-field corpus statistics across segments host-side
(doc counts, Σ field length, per-term df on demand) — what Lucene exposes as
CollectionStatistics/TermStatistics for query-time IDF.
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Any

import jax
import jax.numpy as jnp
import threading

import numpy as np

from elasticsearch_tpu.index.engine import SearcherView
from elasticsearch_tpu.index.segment import Segment


@dataclass
class DeviceTextField:
    tokens: Any      # [Np, L] i32 (position-indexed)
    uterms: Any      # [Np, U] i32
    utf: Any         # [Np, U] f32
    doc_len: Any     # [Np] i32
    column: Any      # host TextFieldColumn (term dict, df)


@dataclass
class DeviceKeywordField:
    ords: Any        # [Np, K] i32
    column: Any      # host KeywordFieldColumn (vocab)


@dataclass
class DeviceNumericField:
    """Numeric doc values as a double-double split: ``hi = f32(v)``,
    ``lo = f32(v - hi)``. TPUs have no fast f64, but lexicographic compare on
    (hi, lo) reproduces exact f64 ordering — epoch-millis dates and large
    longs filter exactly. ``hi`` alone feeds scoring/aggregations."""
    hi: Any          # [Np] f32
    lo: Any          # [Np] f32
    exists: Any      # [Np] bool
    column: Any


def dd_split(v: np.ndarray | float) -> tuple[np.ndarray, np.ndarray]:
    hi = np.float32(v)
    with np.errstate(invalid="ignore"):
        lo = np.float32(np.float64(v) - np.float64(hi))
    # ±inf bounds: inf - inf = nan would poison comparisons; lo 0 keeps the
    # (hi, lo) pair correctly ordered.
    lo = np.where(np.isfinite(np.float64(v)), lo, np.float32(0.0)) \
        if isinstance(v, np.ndarray) else \
        (lo if np.isfinite(v) else np.float32(0.0))
    return hi, lo


@dataclass
class DeviceVectorField:
    vecs: Any        # [Np, D] f32, L2-normalized rows (cosine = dot)
    exists: Any
    column: Any


@dataclass
class DeviceMultiVectorField:
    """rank_vectors column: [Np, T, D] token matrices (each real token
    row L2-normalized so per-token dot = cosine), late-interaction
    scored by the fused MaxSim kernel. ``vecs`` is LAZY like the dense
    vector columns; the knn lane reads its device copy through the
    per-segment block cache (mesh_engine.fetch_vector_block), not this
    field."""
    vecs: Any        # [Np, T, D] f32
    lens: Any        # [Np] i32
    exists: Any
    column: Any


@dataclass
class DeviceGeoField:
    lat: Any
    lon: Any
    exists: Any
    column: Any


@dataclass
class DeviceShapeField:
    lats: Any        # [Np, V] f32 concatenated rings
    lons: Any        # [Np, V] f32
    nv: Any          # [Np] i32 edge slots
    exists: Any
    rid: Any         # [Np, V] i32 ring id (-1 pad)
    area: Any        # [Np, V] bool — ring encloses area
    column: Any


@dataclass
class DeviceNestedBlock:
    """A nested path's child segment + child→parent join, device-resident.
    Child ``live`` already folds the PARENT's live mask in (children of
    deleted parents can never match)."""
    child: "DeviceSegment"
    parent: Any                     # [child Np] i32, -1 pad


@dataclass
class DeviceSegment:
    seg: Segment
    live: Any                       # [Np] bool (padding & deletes False)
    doc_base: int                   # global doc id of row 0 within the reader
    text: dict[str, DeviceTextField]
    keyword: dict[str, DeviceKeywordField]
    numeric: dict[str, DeviceNumericField]
    vector: dict[str, DeviceVectorField]
    geo: dict[str, DeviceGeoField]
    nested: dict[str, "DeviceNestedBlock"] = dc_field(default_factory=dict)
    shape: dict[str, DeviceShapeField] = dc_field(default_factory=dict)
    mvector: dict[str, DeviceMultiVectorField] = dc_field(
        default_factory=dict)
    # device_put for LAZY columns (tokens / vecs): those stay host-side
    # numpy until a plan declares it needs them (jit_exec.seg_flatten
    # materializes + caches on first use). Position matrices and dense
    # vectors dominate column bytes (~450 MB and ~3 GB at 1M docs) and a
    # BM25 query reads neither — eager transfer would serialize the first
    # search behind gigabytes of host→HBM traffic. None (mesh-engine
    # templates) means "arrays are host-side by design, don't touch".
    lazy_put: Any = None
    # False → columns live in a pinned HOST pool, not HBM: the segment is
    # beyond the reader's HBM budget and is streamed host→device per query
    # batch, double-buffered (jit_exec.run_segments_streamed) — the
    # over-capacity analog of the reference's FS-cache paging
    # (core/index/store/FsDirectoryService.java mmap).
    resident: bool = True

    @property
    def padded_docs(self) -> int:
        return self.seg.padded_docs


@dataclass
class TextFieldStats:
    doc_count: int          # docs in reader (incl. not-yet-merged deletes)
    docs_with_field: int
    total_tokens: int

    @property
    def avgdl(self) -> float:
        return self.total_tokens / max(self.docs_with_field, 1)


def resident_prefix_bytes(view: SearcherView,
                          hbm_budget_bytes: int | None) -> int:
    """Column bytes of the segment prefix that stays HBM-resident under a
    budget (mirrors DeviceReader's cutoff: the first segment whose
    cumulative size exceeds the budget — and everything after it —
    streams)."""
    total = 0
    used = 0
    for seg in view.segments:
        b = seg.memory_bytes()
        if hbm_budget_bytes is not None:
            used += b
            if used > hbm_budget_bytes:
                break
        total += b
    return total


class DeviceReader:
    def __init__(self, view: SearcherView, device=None,
                 hbm_budget_bytes: int | None = None):
        """``hbm_budget_bytes`` caps the column bytes uploaded to HBM: a
        PREFIX of segments (in order) is packed device-resident until the
        budget is spent; every later segment stays in a host pool and is
        streamed per query batch. Prefix-order (not best-fit) keeps the
        cross-segment merge's tie-break identical to the fully-resident
        reader: resident candidates always precede streamed ones in
        segment order."""
        self.generation = view.generation
        self.segments: list[DeviceSegment] = []
        self._text_stats: dict[str, TextFieldStats] = {}
        doc_base = 0
        # uploads ride the device-fault seam (lazy import: jit_exec
        # imports this module at load time). Site class reader-upload:
        # this is the RPC fan-out's serving floor — injectable only by
        # explicit p_by_site opt-in, never by the default chaos draw
        from elasticsearch_tpu.search.jit_exec import seam_device_put
        put = lambda x: seam_device_put(            # noqa: E731
            x, device, site="reader-upload")
        self.device = device
        used = 0
        streaming = False
        for seg, live in zip(view.segments, view.live_masks):
            if hbm_budget_bytes is not None and not streaming:
                used += seg.memory_bytes()
                streaming = used > hbm_budget_bytes
            self.segments.append(self._pack_segment(
                seg, live, doc_base, put, resident=not streaming))
            doc_base += seg.padded_docs
        self.max_doc = doc_base
        self._collect_stats(view)

    # ---- packing ----------------------------------------------------------

    def _pack_segment(self, seg: Segment, live: np.ndarray, doc_base: int,
                      put, resident: bool = True) -> DeviceSegment:
        if not resident:
            # host pool: contiguous numpy (one memcpy per DMA later), no
            # device transfer now, no lazy materialization caching
            put = np.ascontiguousarray
        text = {}
        for name, c in seg.text_fields.items():
            text[name] = DeviceTextField(
                tokens=np.ascontiguousarray(c.tokens),    # lazy (see above)
                uterms=put(c.uterms),
                utf=put(c.utf), doc_len=put(c.doc_len), column=c)
        keyword = {name: DeviceKeywordField(ords=put(c.ords), column=c)
                   for name, c in seg.keyword_fields.items()}
        numeric = {}
        for name, c in seg.numeric_fields.items():
            hi, lo = dd_split(c.values)
            numeric[name] = DeviceNumericField(
                hi=put(hi), lo=put(lo), exists=put(c.exists), column=c)
        vector = {}
        for name, c in seg.vector_fields.items():
            norms = np.linalg.norm(c.vecs, axis=1, keepdims=True)
            normed = c.vecs / np.maximum(norms, 1e-12)
            vector[name] = DeviceVectorField(
                vecs=np.ascontiguousarray(normed.astype(np.float32)),  # lazy
                exists=put(c.exists), column=c)
        mvector = {}
        for name, c in seg.mvector_fields.items():
            # per-TOKEN normalization (padding rows stay zero): MaxSim's
            # token dot is then the token cosine, matching the dense lane
            norms = np.linalg.norm(c.vecs, axis=2, keepdims=True)
            normed = c.vecs / np.maximum(norms, 1e-12)
            mvector[name] = DeviceMultiVectorField(
                vecs=np.ascontiguousarray(normed.astype(np.float32)),  # lazy
                lens=put(c.lens), exists=put(c.exists), column=c)
        geo = {name: DeviceGeoField(lat=put(c.lat.astype(np.float32)),
                                    lon=put(c.lon.astype(np.float32)),
                                    exists=put(c.exists), column=c)
               for name, c in seg.geo_fields.items()}
        shape = {name: DeviceShapeField(lats=put(c.lats), lons=put(c.lons),
                                        nv=put(c.nv), exists=put(c.exists),
                                        rid=put(c.rid), area=put(c.area),
                                        column=c)
                 for name, c in seg.shape_fields.items()}
        nested = {}
        for path, blk in seg.nested_blocks.items():
            # child live folds the parent's live mask in: children of
            # deleted parents never match (Lucene deletes the hidden
            # nested docs together with the parent)
            valid = blk.parent >= 0
            child_live = np.zeros(blk.segment.padded_docs, bool)
            child_live[valid] = live[blk.parent[valid]]
            nested[path] = DeviceNestedBlock(
                child=self._pack_segment(blk.segment, child_live, 0, put,
                                         resident=resident),
                parent=put(blk.parent))
        return DeviceSegment(seg=seg, live=put(live), doc_base=doc_base,
                             text=text, keyword=keyword, numeric=numeric,
                             vector=vector, geo=geo, nested=nested,
                             shape=shape, mvector=mvector,
                             lazy_put=put if resident else None,
                             resident=resident)

    def _collect_stats(self, view: SearcherView) -> None:
        for seg in view.segments:
            self._collect_seg_stats(seg)

    def _collect_seg_stats(self, seg: Segment) -> None:
        for name, c in seg.text_fields.items():
            st = self._text_stats.setdefault(name, TextFieldStats(0, 0, 0))
            st.doc_count += seg.num_docs
            st.docs_with_field += int((c.doc_len[:seg.num_docs] > 0).sum())
            st.total_tokens += c.total_tokens
        for blk in seg.nested_blocks.values():
            # nested child fields get their own stats over CHILD rows (the
            # reference's nested docs likewise contribute their own
            # field statistics)
            self._collect_seg_stats(blk.segment)

    # ---- stats (CollectionStatistics / TermStatistics analog) -------------

    @property
    def num_docs(self) -> int:
        return sum(s.seg.num_docs for s in self.segments)

    def text_stats(self, field: str) -> TextFieldStats:
        return self._text_stats.get(field, TextFieldStats(self.num_docs, 0, 0))

    def df(self, field: str, term: str) -> int:
        """Doc frequency aggregated across this reader's segments
        (including nested child blocks — their fields are path-prefixed,
        so names never collide with parent fields)."""
        def seg_df(seg: Segment) -> int:
            out = 0
            col = seg.text_fields.get(field)
            if col is not None:
                tid = col.tid(term)
                if tid >= 0:
                    out += int(col.df[tid])
            for blk in seg.nested_blocks.values():
                out += seg_df(blk.segment)
            return out
        return sum(seg_df(s.seg) for s in self.segments)

    # ---- doc id resolution -------------------------------------------------

    def resolve(self, global_doc: int) -> tuple[DeviceSegment, int]:
        """global doc id → (device segment, local row)."""
        for s in self.segments:
            if s.doc_base <= global_doc < s.doc_base + s.padded_docs:
                return s, global_doc - s.doc_base
        raise IndexError(f"doc {global_doc} out of range")

    def doc_id(self, global_doc: int) -> str:
        s, local = self.resolve(global_doc)
        return s.seg.ids[local]

    def source(self, global_doc: int) -> dict:
        s, local = self.resolve(global_doc)
        return s.seg.sources[local]


def device_reader_for(engine, view: SearcherView | None = None,
                      device=None) -> DeviceReader:
    """Reader cache per refresh generation — columns upload to HBM once per
    refresh, like Lucene's per-commit reader reuse. The cache lives ON the
    engine object so its device arrays are released with the engine (no
    global registry to leak HBM across index delete/create churn)."""
    if view is None:
        view = engine.acquire_searcher()
    # serialize cache swap + breaker accounting (concurrent searches after
    # a refresh must not double-pack or double-account); a dedicated lock,
    # not engine._lock, so packing never blocks writes
    lock = getattr(engine, "_device_reader_lock", None)
    if lock is None:
        lock = engine.__dict__.setdefault("_device_reader_lock",
                                          threading.Lock())
    with lock:
        cached = getattr(engine, "_device_reader_cache", None)
        if cached is not None and cached.generation == view.generation:
            return cached
        # account device-resident column memory against the fielddata
        # breaker (HBM is the scarce resource the reference's fielddata
        # breaker models). Reserve only the DELTA vs the generation being
        # replaced: reserving the full new size while the old is still
        # held would spuriously trip once an index passes half the limit.
        bs = getattr(engine, "breaker_service", None)
        budget = None
        st = getattr(engine, "settings", None)
        if st is not None:
            raw = st.get("index.hbm_budget_bytes", None)
            if raw is not None:
                budget = int(raw)
        # under an HBM budget only the resident prefix occupies HBM —
        # streamed segments live in the host pool plus ~2 transient
        # DMA buffers, so accounting the full corpus would trip the
        # breaker on exactly the over-capacity case streaming exists for
        new_bytes = resident_prefix_bytes(view, budget)
        old_bytes = getattr(cached, "_accounted_bytes", 0) if cached else 0
        if bs is not None:
            # delta accounting rides the device-memory ledger so the
            # reader's resident columns appear in _nodes/stats
            # .device_memory / _cat/hbm next to the block-cache charges
            from elasticsearch_tpu.observability.ledger import \
                account_absolute
            account_absolute(bs, engine.engine_uuid, "reader-columns",
                             old_bytes, new_bytes,
                             f"segments gen {view.generation}")
        if cached is not None:
            # the retiring generation's filter-cache counters fold into a
            # cumulative per-engine tally — ES cache stats survive reader
            # swaps (IndicesQueryCache counts per shard, not per reader)
            old_stats = getattr(cached, "_filter_cache_stats", None)
            if old_stats:
                carry = engine.__dict__.setdefault(
                    "_filter_cache_carry",
                    {"hit_count": 0, "miss_count": 0, "evictions": 0})
                for k in carry:
                    carry[k] += old_stats.get(k, 0)
        cached = DeviceReader(view, device=device, hbm_budget_bytes=budget)
        cached._accounted_bytes = new_bytes if bs is not None else 0
        # impact-lane plumbing: the pack builder keys its device blocks
        # by engine uuid (the PR 5 block-cache discipline) and charges
        # them against the fielddata breaker; the close listener returns
        # every cached block when this engine incarnation dies
        cached.engine_uuid = engine.engine_uuid
        cached.breaker_service = bs
        from elasticsearch_tpu.parallel.mesh_engine import (
            hook_engine_block_release)
        hook_engine_block_release(engine)
        engine._device_reader_cache = cached
        return cached


def release_device_reader(engine) -> None:
    """Drop the engine's cached reader and return its breaker reservation
    (called from Engine.close so budget doesn't leak across index
    delete/create churn). Takes the same lock as device_reader_for so a
    concurrent packer can't install a new reader+reservation between our
    read and clear (which would leak or double-release breaker bytes)."""
    lock = engine.__dict__.setdefault("_device_reader_lock",
                                      threading.Lock())
    with lock:
        cached = getattr(engine, "_device_reader_cache", None)
        bs = getattr(engine, "breaker_service", None)
        if cached is not None and bs is not None:
            from elasticsearch_tpu.observability.ledger import \
                account_absolute
            account_absolute(bs, engine.engine_uuid, "reader-columns",
                             getattr(cached, "_accounted_bytes", 0), 0,
                             "reader close")
        if cached is not None:
            engine._device_reader_cache = None
