from elasticsearch_tpu.index.segment import Segment, SegmentBuilder, TextFieldColumn
from elasticsearch_tpu.index.translog import Translog
from elasticsearch_tpu.index.engine import Engine

__all__ = ["Segment", "SegmentBuilder", "TextFieldColumn", "Translog", "Engine"]
