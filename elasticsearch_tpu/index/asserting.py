"""AssertingEngine — the MockEngineSupport / AssertingSearcher analog.

The reference's test framework wraps every engine and searcher in
asserting shims (test/test/engine/MockEngineSupport.java,
AssertingSearcher: searcher-leak checks, invariant assertions on every
read) injected through the normal engine-factory seam. Here the same
seam is the ``index.engine.type: asserting`` setting
(IndicesService.add_local_shard): tests get an Engine that checks
invariants on every operation and accounts searcher acquisitions, and
the in-process test cluster (testing.InternalTestCluster) runs leak
checks at teardown.
"""

from __future__ import annotations

import threading

from elasticsearch_tpu.index.engine import Engine


class AssertingEngine(Engine):
    """Engine wrapper asserting cross-operation invariants:

    * version monotonicity — a successful index op must leave the doc at
      a strictly higher version than before;
    * live accounting — after every refresh, each searcher view's live
      rows must sum to exactly ``doc_count`` and live masks must match
      their segments' padded row counts;
    * searcher accounting — acquisitions are counted per generation
      (the AssertingSearcher ledger; read via ``searcher_acquisitions``).
    """

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._assert_lock = threading.Lock()
        self.searcher_acquisitions: dict[int, int] = {}

    # ---- invariant helpers ------------------------------------------------

    def _assert_live_consistency(self) -> None:
        view = super().acquire_searcher()
        live_total = 0
        for seg, mask in zip(view.segments, view.live_masks):
            assert mask.shape[0] == seg.padded_docs, \
                f"live mask rows {mask.shape[0]} != padded " \
                f"{seg.padded_docs} (seg {seg.seg_id})"
            assert not mask[seg.num_docs:].any(), \
                f"padding rows alive in seg {seg.seg_id}"
            live_total += int(mask.sum())
        # doc-count comparison only when no writer raced the refresh:
        # buffered-but-unrefreshed docs (or a generation bump) mean the
        # view and the versions map legitimately disagree
        with self._lock:
            stable = len(self._buffer) == 0 and \
                self._reader.generation == view.generation and \
                not getattr(self, "_pending_seg_deletes", None)
            dc = sum(1 for e in self._versions.values() if not e.deleted)
        if stable:
            assert live_total == dc, \
                f"live rows {live_total} != doc_count {dc}"

    # ---- wrapped operations ----------------------------------------------

    def index(self, doc_id, source, **kw):
        before = self.doc_version(doc_id)
        out = super().index(doc_id, source, **kw)
        # judge by the version THE OP returned, not a re-read (a racing
        # delete would turn a re-read None). Strict before<after only
        # holds when nothing interleaved: internal versions RESTART at 1
        # after a delete tombstone, so under concurrency we can only
        # require a valid version
        new_version = out[0] if isinstance(out, tuple) else out
        assert new_version is not None and new_version >= 1, \
            f"index op returned version [{new_version}] for [{doc_id}]"
        if before is not None and new_version <= before:
            # a regression is only legal as the version-1 restart after
            # an interleaved delete tombstone
            assert new_version == 1, \
                f"version regressed for [{doc_id}]: " \
                f"{before} -> {new_version}"
        return out

    def refresh(self):
        out = super().refresh()
        self._assert_live_consistency()
        return out

    def acquire_searcher(self):
        view = super().acquire_searcher()
        with self._assert_lock:
            self.searcher_acquisitions[view.generation] = \
                self.searcher_acquisitions.get(view.generation, 0) + 1
        return view


def engine_class_for(settings) -> type[Engine]:
    """The engine-factory seam (IndexModule.engineFactoryImpl,
    core/index/IndexModule.java:37): ``index.engine.type: asserting``
    swaps in the asserting wrapper, anything else gets the real engine."""
    if settings is not None and \
            settings.get("index.engine.type", "") == "asserting":
        return AssertingEngine
    return Engine
