"""elasticsearch_tpu — a TPU-native distributed search & analytics engine.

A from-scratch re-design of the Elasticsearch capability surface
(reference: infusionsoft/elasticsearch, ES 3.0.0-SNAPSHOT on Lucene 5.4)
for TPU hardware:

* The Lucene-equivalent index/score/top-k kernels are JAX/XLA programs over
  **dense, padded columnar segments resident in HBM** (see
  :mod:`elasticsearch_tpu.index.segment` and :mod:`elasticsearch_tpu.ops`).
  Queries compile to dense compares / reductions / matmuls producing per-doc
  ``(score, mask)`` vectors, then ``lax.top_k`` — no pointer chasing, no
  dynamic shapes, exact results.
* Sharding (the reference's hash-partitioned shards,
  core/cluster/routing/OperationRouting.java:238) maps to a mesh axis:
  scatter-gather query fan-out + top-k merge
  (core/action/search/type/TransportSearchTypeAction.java:137,
  core/search/controller/SearchPhaseController.java:165) becomes
  ``shard_map`` + ``all_gather`` inside a single jitted program
  (:mod:`elasticsearch_tpu.parallel`).
* The host side (Python) owns what the reference's JVM owns: REST API,
  cluster state, mapping/analysis, segment building, translog, recovery.
"""

__version__ = "0.1.0"

from elasticsearch_tpu.common.versioning import Version, CURRENT_VERSION

__all__ = ["Version", "CURRENT_VERSION", "__version__"]
