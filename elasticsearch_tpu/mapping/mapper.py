"""Mapping: JSON documents → typed, indexable field values.

The reference's mapper (core/index/mapper/MapperService.java,
DocumentMapper.java) turns a JSON source into Lucene fields, infers mappings
dynamically for unseen fields, and merges mapping updates. Ours turns JSON
into **columnar segment inputs**:

* ``text``      → analyzed token stream (positions kept) → token matrix rows
* ``keyword``   → exact values → ordinal doc-values column (also ES 2.x
                  ``string`` with ``index: not_analyzed``)
* numerics/date/boolean → float64 doc-values column + exists bitmap
* ``dense_vector`` → fixed-dim float32 row in the vector matrix
* ``geo_point`` → (lat, lon) pair of float64 columns

Metadata fields (_id, _source, _routing, _version) are handled by the engine,
matching the reference's internal mappers (core/index/mapper/internal/).
"""

from __future__ import annotations

import datetime as _dt
import numbers
from dataclasses import dataclass, field
from typing import Any, Mapping

import numpy as np

from json import dumps as _json_dumps

from elasticsearch_tpu.utils.murmur3 import hash128_x64_h1

from elasticsearch_tpu.analysis import AnalysisRegistry, Token
from elasticsearch_tpu.common.errors import MapperParsingError, IllegalArgumentError
from elasticsearch_tpu.common.settings import parse_bool

# Field kinds the segment builder understands.
KIND_TEXT = "text"
KIND_KEYWORD = "keyword"
KIND_NUMERIC = "numeric"   # long/integer/short/byte/double/float/date/boolean
KIND_VECTOR = "vector"
KIND_MVECTOR = "mvector"   # rank_vectors: per-doc [T, D] token matrices
KIND_GEO = "geo"
KIND_SHAPE = "shape"

#: dense_vector / rank_vectors dims ceiling — bounds the per-doc row the
#: MXU matmuls over (and the create-request 400 for absurd mappings)
MAX_VECTOR_DIMS = 4096
#: rank_vectors token cap ceiling (per-doc [T, D] matrices are padded to
#: the mapping's max_tokens, so T is HBM — keep it bounded)
MAX_RANK_VECTOR_TOKENS = 512
DEFAULT_RANK_VECTOR_TOKENS = 32

NUMERIC_TYPES = {"long", "integer", "short", "byte", "double", "float",
                 "half_float", "date", "boolean", "murmur3", "ip",
                 "token_count"}
KIND_BINARY = "binary"


def ip_to_long(v) -> int:
    """Dotted-quad IPv4 → long, the reference's IpFieldMapper.ipToLong
    (core/index/mapper/ip/IpFieldMapper.java) — indexed as a numeric
    doc value so ranges and CIDR terms are ordinary numeric intervals."""
    parts = str(v).split(".")
    if len(parts) != 4:
        raise MapperParsingError(f"failed to parse ip [{v}]")
    out = 0
    for p in parts:
        try:
            b = int(p)
        except ValueError:
            raise MapperParsingError(f"failed to parse ip [{v}]") \
                from None
        if not 0 <= b <= 255:
            raise MapperParsingError(f"failed to parse ip [{v}]")
        out = (out << 8) | b
    return out


def cidr_range(v: str) -> tuple[int, int]:
    """'a.b.c.d/n' → (network, broadcast) longs."""
    addr, _, bits = str(v).partition("/")
    try:
        n = int(bits)
    except ValueError:
        raise MapperParsingError(f"invalid CIDR mask [{v}]") from None
    if not 0 <= n <= 32:
        raise MapperParsingError(f"invalid CIDR mask [{v}]")
    base = ip_to_long(addr)
    mask = ((1 << 32) - 1) ^ ((1 << (32 - n)) - 1)
    lo = base & mask
    return lo, lo | ((1 << (32 - n)) - 1)

POSITION_INCREMENT_GAP = 16


def _vector_dims(name: str, ftype: str, params) -> int:
    """Validate a vector mapping's ``dims`` at CREATE time with the
    400-typed error idiom (store.type / impact settings): a bad value
    must fail the create/mapping request, never surface later as a
    score-time shape error."""
    raw = params.get("dims", 0)
    try:
        dims = int(raw)
    except (TypeError, ValueError):
        raise IllegalArgumentError(
            f"{ftype} field [{name}] dims must be an integer, "
            f"got [{raw}]") from None
    if dims <= 0:
        raise MapperParsingError(f"{ftype} field [{name}] requires dims")
    if dims > MAX_VECTOR_DIMS:
        raise IllegalArgumentError(
            f"{ftype} field [{name}] dims must be <= {MAX_VECTOR_DIMS}, "
            f"got {dims}")
    return dims


def completion_context_value(cfg: dict, raw) -> str:
    """One context dimension's value → its index key component."""
    if cfg.get("type") == "geo":
        from elasticsearch_tpu.utils.geohash import (
            geohash_encode, precision_to_length)
        length = precision_to_length(cfg.get("precision", "1km"))
        if isinstance(raw, dict):
            lat, lon = float(raw.get("lat")), float(raw.get("lon"))
        elif isinstance(raw, (list, tuple)):
            lon, lat = float(raw[0]), float(raw[1])
        else:
            return str(raw)[:length]          # already a geohash
        return geohash_encode(lat, lon, length)
    return str(raw)


def completion_context_keys(cfg: dict, provided: dict,
                            path_values: dict | None = None) -> list[str]:
    """Context config + per-value context → the key prefixes an input is
    indexed under (one per combination; ref: ContextMapping.parseContext).
    A `path` dimension with no resolved value yet yields a placeholder the
    DocumentMapper post-pass replaces from the doc source."""
    dims: list[list[str]] = []
    for name in sorted(cfg):
        c = cfg[name] or {}
        raw = provided.get(name)
        if raw is None and path_values and name in path_values:
            raw = path_values[name]
        if raw is None and c.get("path"):
            dims.append([f"\x00PATH:{name}"])
            continue
        if raw is None:
            raw = c.get("default", "")
        vals = raw if isinstance(raw, list) else [raw]
        dims.append([completion_context_value(c, v) for v in vals])
    keys = [""]
    for vals in dims:
        keys = [f"{k}\x1d{v}" if k else str(v)
                for k in keys for v in vals]
    return keys


def parse_date(value: Any) -> float:
    """→ epoch millis (float). Accepts epoch millis, ISO-8601, yyyy-MM-dd."""
    if isinstance(value, bool):
        raise MapperParsingError(f"cannot parse date from boolean [{value}]")
    if isinstance(value, numbers.Number):
        return float(value)
    s = str(value)
    for parser in (
        lambda v: _dt.datetime.fromisoformat(v.replace("Z", "+00:00")),
        lambda v: _dt.datetime.strptime(v, "%Y-%m-%d"),
        lambda v: _dt.datetime.strptime(v, "%Y-%m-%d %H:%M:%S"),
    ):
        try:
            dt = parser(s)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=_dt.timezone.utc)
            return dt.timestamp() * 1000.0
        except ValueError:
            continue
    try:
        return float(s)  # epoch millis as string
    except ValueError:
        raise MapperParsingError(f"failed to parse date field [{value}]") from None


@dataclass
class ParsedField:
    name: str
    kind: str
    tokens: list[Token] = field(default_factory=list)      # KIND_TEXT
    keywords: list[str] = field(default_factory=list)       # KIND_KEYWORD
    numerics: list[float] = field(default_factory=list)     # KIND_NUMERIC
    vector: np.ndarray | None = None                        # KIND_VECTOR
    mvector: np.ndarray | None = None                       # KIND_MVECTOR [T, D]
    geo: tuple[float, float] | None = None                  # KIND_GEO (lat, lon)
    # KIND_SHAPE: (lats, lons) closed vertex ring (utils/geoshape)
    shape: tuple[list[float], list[float]] | None = None


@dataclass
class ParsedDocument:
    doc_id: str
    source: dict
    fields: dict[str, ParsedField]
    routing: str | None = None
    # nested path → one field-dict per nested object (each becomes a row
    # of the segment's child block; ref: ObjectMapper Nested,
    # core/index/mapper/object/ObjectMapper.java — nested objects are
    # separate hidden docs adjacent to their parent)
    nested: dict[str, list[dict[str, ParsedField]]] = field(
        default_factory=dict)


class FieldMapper:
    """One field's mapping entry."""

    def __init__(self, name: str, ftype: str, params: Mapping[str, Any],
                 analysis: AnalysisRegistry):
        self.name = name
        self.type = ftype
        self.params = dict(params)
        # ES 2.x "string" splits into text vs keyword on index: not_analyzed
        # (reference: core/index/mapper/core/StringFieldMapper.java).
        if ftype == "string":
            self.type = "keyword" if params.get("index") == "not_analyzed" else "text"
        elif ftype == "multi_field":
            # pre-1.0 multi_field syntax (still accepted in 2.x): the
            # sub-field named like the field is the main mapping
            main = (params.get("fields") or {}).get(name.split(".")[-1], {})
            self.type = "keyword" if main.get("index") == "not_analyzed" \
                else "text"
        if self.type == "text":
            self.kind = KIND_TEXT
            self.analyzer = analysis.get(params.get("analyzer", "standard"))
            self.search_analyzer = analysis.get(
                params.get("search_analyzer", params.get("analyzer", "standard")))
        elif self.type in ("keyword", "completion"):
            # completion (suggest) inputs are stored as exact values; the
            # suggester prefix-scans the sorted vocab, standing in for the
            # reference's FST-backed CompletionFieldMapper
            self.kind = KIND_KEYWORD
            # context suggester config (ContextMappings, 2.x "context" on
            # completion fields): {name: {type: category|geo, default?,
            # path?, precision?}}
            self.context_config = params.get("context") \
                if self.type == "completion" else None
        elif self.type in NUMERIC_TYPES:
            self.kind = KIND_NUMERIC
            if self.type == "token_count":
                # TokenCountFieldMapper: analyze the string, index the
                # token count as a numeric doc value
                self.analyzer = analysis.get(
                    params.get("analyzer", "standard"))
        elif self.type == "binary":
            # BinaryFieldMapper: stored in _source only (not indexed, no
            # doc values by default — matches the reference's defaults)
            self.kind = KIND_BINARY
        elif self.type == "dense_vector":
            self.kind = KIND_VECTOR
            self.dims = _vector_dims(name, "dense_vector", params)
        elif self.type == "rank_vectors":
            # multi-vector late-interaction mapping: each doc carries a
            # [T, D] token matrix (ColBERT-style), padded/bucketed like
            # the uterms columns; scored by the fused MaxSim kernel
            # (ops/maxsim.py) through the top-level `knn` search section
            self.kind = KIND_MVECTOR
            self.dims = _vector_dims(name, "rank_vectors", params)
            raw_mt = params.get("max_tokens", DEFAULT_RANK_VECTOR_TOKENS)
            try:
                self.max_tokens = int(raw_mt)
            except (TypeError, ValueError):
                raise IllegalArgumentError(
                    f"rank_vectors field [{name}] max_tokens must be an "
                    f"integer, got [{raw_mt}]") from None
            if not 1 <= self.max_tokens <= MAX_RANK_VECTOR_TOKENS:
                raise IllegalArgumentError(
                    f"rank_vectors field [{name}] max_tokens must be in "
                    f"[1, {MAX_RANK_VECTOR_TOKENS}], got {self.max_tokens}")
        elif self.type == "geo_point":
            self.kind = KIND_GEO
        elif self.type == "geo_shape":
            self.kind = KIND_SHAPE
        else:
            raise MapperParsingError(f"no handler for type [{ftype}] on field [{name}]")
        # Multi-fields: {"fields": {"raw": {"type": "keyword"}}}
        self.sub_fields: dict[str, FieldMapper] = {}
        for sub_name, sub_def in params.get("fields", {}).items():
            self.sub_fields[sub_name] = FieldMapper(
                f"{name}.{sub_name}", sub_def.get("type", "keyword"), sub_def, analysis)

    def to_dict(self) -> dict:
        # render the type the mapping was PUT with (2.x "string" stays
        # "string" even though it resolved to text/keyword internally;
        # legacy multi_field renders as string like the reference upgrade)
        rendered = self.params.get("type", self.type)
        if rendered == "multi_field":
            rendered = "string"
        out = {"type": rendered,
               **{k: v for k, v in self.params.items()
                  if k not in ("type", "fields")}}
        if self.sub_fields:
            out["fields"] = {n.split(".")[-1]: m.to_dict()
                             for n, m in self.sub_fields.items()}
        return out

    # ---- value parsing ----------------------------------------------------

    def parse_value(self, value: Any) -> ParsedField:
        pf = ParsedField(self.name, self.kind)
        if self.kind in (KIND_VECTOR, KIND_MVECTOR):
            values = [value]
        elif self.kind == KIND_GEO and isinstance(value, (list, tuple)) \
                and len(value) == 2 and all(isinstance(x, numbers.Number)
                                            for x in value):
            values = [value]  # flat GeoJSON pair [lon, lat], not a multi-value
        elif isinstance(value, list):
            values = value
        else:
            values = [value]
        if self.kind == KIND_TEXT:
            position = 0
            for v in values:
                if v is None:
                    continue
                toks = self.analyzer.analyze(str(v))
                # Position gap between array elements blocks phrase matches
                # across elements (Lucene's position_increment_gap, default
                # 100 there; 16 here because the segment layout is
                # position-indexed and slots are memory).
                for t in toks:
                    pf.tokens.append(Token(t.term, t.position + position,
                                           t.start_offset, t.end_offset))
                if toks:
                    position += toks[-1].position + POSITION_INCREMENT_GAP
        elif self.kind == KIND_KEYWORD:
            if self.type == "completion":
                # completion accepts "text", ["a","b"], or
                # {"input": [...], "weight": N} (CompletionFieldMapper
                # parse shapes); weights degrade to doc frequency here
                flat: list[str] = []
                for v in values:
                    inputs: list[str]
                    provided_ctx: dict = {}
                    if isinstance(v, dict):
                        inp = v.get("input", [])
                        inputs = [inp] if isinstance(inp, str) else \
                            [str(x) for x in inp]
                        provided_ctx = v.get("context") or {}
                    elif v is not None:
                        inputs = [str(v)]
                    else:
                        continue
                    cfg = getattr(self, "context_config", None)
                    # match keys are lowercased (CompletionFieldMapper's
                    # default "simple" index analyzer); the original text
                    # rides after \x1e for display
                    encoded = [f"{i.lower()}\x1e{i}" for i in inputs]
                    if cfg:
                        keys = completion_context_keys(cfg, provided_ctx)
                        flat.extend(f"{key}\x1f{e}" for key in keys
                                    for e in encoded)
                    else:
                        flat.extend(encoded)
                pf.keywords = flat
            else:
                pf.keywords = [str(v) for v in values if v is not None]
        elif self.kind == KIND_NUMERIC:
            for v in values:
                if v is None:
                    continue
                if self.type == "date":
                    pf.numerics.append(parse_date(v))
                elif self.type == "boolean":
                    try:
                        pf.numerics.append(1.0 if parse_bool(v, self.name) else 0.0)
                    except IllegalArgumentError:
                        raise MapperParsingError(
                            f"failed to parse [{self.name}] value [{v}] as boolean"
                        ) from None
                elif self.type == "ip":
                    if isinstance(v, (int, float)):
                        pf.numerics.append(float(v))
                    else:
                        pf.numerics.append(float(ip_to_long(v)))
                elif self.type == "token_count":
                    pf.numerics.append(
                        float(len(self.analyzer.analyze(str(v)))))
                elif self.type == "murmur3":
                    # mapper-murmur3 plugin: index hash128(value).h1 as a
                    # long doc-value (Murmur3FieldMapper.java:137) — feeds
                    # cardinality aggs on pre-hashed values. f64 storage
                    # keeps 53 of the 64 bits; collisions stay negligible
                    # for distinct-count purposes
                    pf.numerics.append(
                        float(hash128_x64_h1(str(v).encode("utf-8"))))
                else:
                    try:
                        pf.numerics.append(float(v))
                    except (TypeError, ValueError):
                        raise MapperParsingError(
                            f"failed to parse [{self.name}] value [{v}] as {self.type}"
                        ) from None
        elif self.kind == KIND_VECTOR:
            arr = np.asarray(value, dtype=np.float32)
            if arr.shape != (self.dims,):
                raise MapperParsingError(
                    f"dense_vector [{self.name}] expects dims [{self.dims}], "
                    f"got shape {arr.shape}")
            pf.vector = arr
        elif self.kind == KIND_MVECTOR:
            try:
                arr = np.asarray(value, dtype=np.float32)
            except (TypeError, ValueError):
                raise MapperParsingError(
                    f"rank_vectors [{self.name}] expects a list of "
                    f"[{self.dims}]-dim vectors") from None
            if arr.ndim == 1:              # one token: [D] → [1, D]
                arr = arr[None, :]
            if arr.ndim != 2 or arr.shape[1] != self.dims or \
                    arr.shape[0] == 0:
                raise MapperParsingError(
                    f"rank_vectors [{self.name}] expects [T, {self.dims}] "
                    f"token vectors, got shape {arr.shape}")
            # token cap is a mapping contract like text max_tokens:
            # overflow truncates (index-time), never errors
            pf.mvector = arr[:self.max_tokens]
        elif self.kind == KIND_SHAPE:
            from elasticsearch_tpu.utils.geoshape import parse_shape_rings
            v = value if isinstance(value, dict) else values[0]
            if not isinstance(v, dict):
                raise MapperParsingError(
                    f"cannot parse geo_shape [{value!r}]")
            try:
                pf.shape = parse_shape_rings(v)
            except Exception as e:
                raise MapperParsingError(
                    f"failed to parse geo_shape [{self.name}]: {e}") \
                    from None
        elif self.kind == KIND_GEO:
            v = values[0]
            if isinstance(v, dict):
                pf.geo = (float(v["lat"]), float(v["lon"]))
            elif isinstance(v, str):
                lat, lon = v.split(",")
                pf.geo = (float(lat), float(lon))
            elif isinstance(v, (list, tuple)):  # GeoJSON order [lon, lat]
                pf.geo = (float(v[1]), float(v[0]))
            else:
                raise MapperParsingError(f"cannot parse geo_point [{value}]")
        return pf


def validate_vector_mappings(mappings: Mapping[str, Any]) -> None:
    """Create-index-time validation of vector field mappings (the
    store.type / impact-settings idiom): dims bounds and rank_vectors
    token caps must fail the CREATE REQUEST with the 400-typed error —
    the cluster-state applier swallows exceptions, so a bad mapping
    validated only there would silently produce a broken index."""
    def walk(props: Mapping[str, Any]) -> None:
        for name, fdef in (props or {}).items():
            if not isinstance(fdef, Mapping):
                continue
            ftype = fdef.get("type")
            if ftype in ("dense_vector", "rank_vectors"):
                # constructing the mapper runs the full validation
                FieldMapper(name, ftype, fdef, _VALIDATION_ANALYSIS)
            if "properties" in fdef:
                walk(fdef["properties"])
    for _type, m in (mappings or {}).items():
        if isinstance(m, Mapping):
            walk(m.get("properties", {}))


class _LazyAnalysis:
    """Deferred AnalysisRegistry for the validation probe (vector
    mappings never touch analyzers, so none is ever built)."""

    def get(self, name):
        return AnalysisRegistry().get(name)


_VALIDATION_ANALYSIS = _LazyAnalysis()


class DocumentMapper:
    """Per-type document mapping (reference: DocumentMapper.java)."""

    def __init__(self, type_name: str, mapping_def: Mapping[str, Any],
                 analysis: AnalysisRegistry, dynamic: bool = True):
        self.type_name = type_name
        self.analysis = analysis
        self.root: dict[str, Any] = dict(mapping_def)
        self.dynamic = {"true": True, "false": False, "strict": "strict"}.get(
            str(mapping_def.get("dynamic", dynamic)).lower(), True)
        self.mappers: dict[str, FieldMapper] = {}
        # paths mapped {"type": "nested"} — their objects index as child
        # rows (segment nested blocks), not flattened parent fields
        self.nested_paths: set[str] = set()
        # metadata-field configs (ref: core/index/mapper/internal/
        # {Parent,Timestamp,TTL}FieldMapper): _parent joins this type to a
        # parent type; _timestamp/_ttl stamp per-doc numeric columns
        p = mapping_def.get("_parent") or {}
        self.parent_type: str | None = p.get("type")
        def _on(v):
            return str(v).lower() in ("true", "1", "yes", "on")
        ts = mapping_def.get("_timestamp") or {}
        self.timestamp_enabled = _on(ts.get("enabled", "false"))
        self.timestamp_default: str | None = ts.get("default")
        ttl = mapping_def.get("_ttl") or {}
        self.ttl_enabled = _on(ttl.get("enabled", "false"))
        self.ttl_default: str | None = ttl.get("default")
        # mapper-size plugin: {"_size": {"enabled": true}} indexes the
        # source byte length as a long doc-value under _size
        # (plugins/mapper-size/.../SizeFieldMapper.java)
        self.size_enabled = _on((mapping_def.get("_size") or {})
                                .get("enabled", "false"))
        self._build(mapping_def.get("properties", {}), prefix="")

    def _build(self, properties: Mapping[str, Any], prefix: str,
               in_nested: bool = False) -> None:
        for name, fdef in properties.items():
            full = f"{prefix}{name}"
            if fdef.get("type") == "nested":
                if in_nested:
                    # reject up front: a silently-dropped inner block would
                    # make data unsearchable with no error
                    raise MapperParsingError(
                        f"nested field [{full}] inside a nested field is "
                        f"not supported")
                self.nested_paths.add(full)
                self._build(fdef.get("properties", {}), prefix=f"{full}.",
                            in_nested=True)
                continue
            if "properties" in fdef and "type" not in fdef:   # object field
                self._build(fdef["properties"], prefix=f"{full}.",
                            in_nested=in_nested)
                continue
            self.add_mapper(FieldMapper(full, fdef.get("type", "text"), fdef,
                                        self.analysis))

    def add_mapper(self, mapper: FieldMapper) -> None:
        self.mappers[mapper.name] = mapper
        for sub in mapper.sub_fields.values():
            self.mappers[sub.name] = sub

    # ---- dynamic mapping inference (DocumentParser dynamic templates) -----

    def _infer(self, name: str, value: Any) -> FieldMapper | None:
        if value is None:
            return None
        if isinstance(value, list):
            if not value:
                return None
            value = value[0]
        if isinstance(value, bool):
            ftype = "boolean"
        elif isinstance(value, int):
            ftype = "long"
        elif isinstance(value, float):
            ftype = "double"
        elif isinstance(value, str):
            # date detection mirrors the reference's dynamic date formats
            try:
                parse_date(value)
                is_date = any(c in value for c in "-:T") and value[:4].isdigit()
            except MapperParsingError:
                is_date = False
            ftype = "date" if is_date else "text"
        else:
            return None
        params = {"type": ftype}
        if ftype == "text":
            # dynamic strings get a .keyword sub-field (modern ES default)
            params["fields"] = {"keyword": {"type": "keyword"}}
        return FieldMapper(name, ftype, params, self.analysis)

    # ---- parse ------------------------------------------------------------

    def parse(self, doc_id: str, source: Mapping[str, Any],
              routing: str | None = None,
              meta: Mapping[str, Any] | None = None) -> ParsedDocument:
        fields: dict[str, ParsedField] = {}
        nested: dict[str, list[dict[str, ParsedField]]] = {}
        new_mappers: list[FieldMapper] = []
        self._parse_object(source, "", fields, new_mappers, nested)
        for m in new_mappers:        # dynamic mapping update
            self.add_mapper(m)
        # resolve completion-context `path` placeholders from the doc
        # source (ContextMapping path references another field's value)
        for fname, pf in fields.items():
            if not pf.keywords or "\x00PATH:" not in "".join(pf.keywords):
                continue
            fm = self.mappers.get(fname)
            cfg = getattr(fm, "context_config", None) or {}
            resolved = []
            for key in pf.keywords:
                for name, c in cfg.items():
                    ph = f"\x00PATH:{name}"
                    if ph in key:
                        raw = source.get(c.get("path", ""))
                        if raw is None:
                            raw = c.get("default", "")
                        key = key.replace(
                            ph, completion_context_value(c, raw))
                resolved.append(key)
            pf.keywords = resolved
        if meta:
            # metadata fields index as ordinary columns under their
            # reserved names — _type/_parent keyword, _timestamp/_ttl
            # numeric — so type filters, parent joins, and TTL sweeps are
            # plain device queries (the reference's internal field mappers
            # do the same with Lucene fields)
            for key in ("_type", "_parent", "_routing"):
                v = meta.get(key)
                if v is not None:
                    fields[key] = ParsedField(name=key, kind="keyword",
                                              keywords=[str(v)])
            for key in ("_timestamp", "_ttl", "_version"):
                v = meta.get(key)
                if v is not None:
                    fields[key] = ParsedField(name=key, kind="numeric",
                                              numerics=[float(v)])
        if self.size_enabled:
            # the REST layer threads the on-the-wire source length in as
            # meta._source_bytes (what SizeFieldMapper measures); embedded
            # callers without raw bytes fall back to a compact UTF-8
            # re-serialization (ensure_ascii would inflate non-ASCII ~3x)
            raw_len = (meta or {}).get("_source_bytes")
            fields["_size"] = ParsedField(
                name="_size", kind="numeric",
                numerics=[float(raw_len if raw_len is not None else
                                len(_json_dumps(
                                    source, separators=(",", ":"),
                                    ensure_ascii=False).encode("utf-8")))])
        return ParsedDocument(doc_id=doc_id, source=dict(source), fields=fields,
                              routing=routing, nested=nested)

    def _parse_object(self, obj: Mapping[str, Any], prefix: str,
                      out: dict[str, ParsedField],
                      new_mappers: list[FieldMapper],
                      nested: dict[str, list[dict[str, ParsedField]]]
                      | None = None) -> None:
        for key, value in obj.items():
            full = f"{prefix}{key}"
            if nested is not None and full in self.nested_paths:
                objs = value if isinstance(value, list) else [value]
                rows = nested.setdefault(full, [])
                for sub in objs:
                    if not isinstance(sub, Mapping):
                        raise MapperParsingError(
                            f"nested field [{full}] expects objects")
                    row: dict[str, ParsedField] = {}
                    self._parse_object(sub, f"{full}.", row, new_mappers,
                                       nested=None)
                    rows.append(row)
                continue
            if isinstance(value, Mapping) and full not in self.mappers:
                self._parse_object(value, f"{full}.", out, new_mappers,
                                   nested)
                continue
            mapper = self.mappers.get(full)
            if mapper is None:
                if self.dynamic == "strict":
                    raise MapperParsingError(
                        f"mapping set to strict, dynamic introduction of [{full}] "
                        f"within [{self.type_name}] is not allowed")
                if not self.dynamic:
                    continue
                mapper = self._infer(full, value)
                if mapper is None:
                    continue
                new_mappers.append(mapper)
            out[full] = mapper.parse_value(value)
            for sub in mapper.sub_fields.values():
                out[sub.name] = sub.parse_value(value)

    def mapping_dict(self) -> dict:
        props: dict[str, Any] = {}
        for path in sorted(self.nested_paths):
            node = props
            parts = path.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = {"type": "nested"}
        for name, m in self.mappers.items():
            if "." in name and name.rsplit(".", 1)[0] in self.mappers:
                continue  # sub-field, rendered inside parent
            node = props
            parts = name.split(".")
            for p in parts[:-1]:
                node = node.setdefault(p, {}).setdefault("properties", {})
            node[parts[-1]] = m.to_dict()
        # an empty mapping renders as {} (the reference omits `properties`)
        return {"properties": props} if props else {}


class MapperService:
    """Per-index mapping registry + merge (reference: MapperService.java).

    ES 2.x is multi-type; modern ES is single-type. We accept any type name
    but default to ``_doc``.
    """

    DEFAULT_TYPE = "_doc"

    def __init__(self, analysis: AnalysisRegistry | None = None):
        self.analysis = analysis or AnalysisRegistry()
        self.mappers: dict[str, DocumentMapper] = {}

    def merge(self, type_name: str, mapping_def: Mapping[str, Any]) -> DocumentMapper:
        existing = self.mappers.get(type_name)
        if existing is None:
            dm = DocumentMapper(type_name, mapping_def, self.analysis)
            self.mappers[type_name] = dm
            return dm
        # merge: new fields added; conflicting type changes rejected;
        # object fields (properties w/o type) recurse like DocumentMapper._build
        self._merge_properties(existing, mapping_def.get("properties", {}), "")
        return existing

    def _merge_properties(self, existing: DocumentMapper,
                          properties: Mapping[str, Any], prefix: str) -> None:
        for name, fdef in properties.items():
            full = f"{prefix}{name}"
            if fdef.get("type") == "nested":
                if any(full.startswith(f"{p}.") for p in
                       existing.nested_paths):
                    raise MapperParsingError(
                        f"nested field [{full}] inside a nested field is "
                        f"not supported")
                existing.nested_paths.add(full)
                self._merge_properties(existing, fdef.get("properties", {}),
                                       f"{full}.")
                continue
            if "properties" in fdef and "type" not in fdef:   # object field
                self._merge_properties(existing, fdef["properties"], f"{full}.")
                continue
            old = existing.mappers.get(full)
            new = FieldMapper(full, fdef.get("type", "text"), fdef, self.analysis)
            if old is not None and old.type != new.type:
                raise IllegalArgumentError(
                    f"mapper [{full}] cannot be changed from type "
                    f"[{old.type}] to [{new.type}]")
            existing.add_mapper(new)

    def document_mapper(self, type_name: str | None = None) -> DocumentMapper:
        tname = type_name or self.DEFAULT_TYPE
        if tname not in self.mappers:
            if type_name is None and len(self.mappers) == 1:
                # untyped op against an index mapped with ONE custom type:
                # that type IS the document mapping (single-type
                # semantics — the 2.x type name is a surface label here)
                return next(iter(self.mappers.values()))
            self.mappers[tname] = DocumentMapper(tname, {}, self.analysis)
        return self.mappers[tname]

    def field_mapper(self, field_name: str) -> FieldMapper | None:
        for dm in self.mappers.values():
            if field_name in dm.mappers:
                return dm.mappers[field_name]
        return None

    def mapping_dict(self) -> dict:
        return {t: dm.mapping_dict() for t, dm in self.mappers.items()}
