from elasticsearch_tpu.mapping.mapper import (
    MapperService,
    DocumentMapper,
    FieldMapper,
    ParsedDocument,
    ParsedField,
)

__all__ = [
    "MapperService",
    "DocumentMapper",
    "FieldMapper",
    "ParsedDocument",
    "ParsedField",
]
