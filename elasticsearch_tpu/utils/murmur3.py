"""MurmurHash3 x64_128 (h1 only), seed 0.

The mapper-murmur3 plugin indexes ``MurmurHash3.hash128(utf8 bytes).h1``
as a long doc-value (plugins/mapper-murmur3/.../Murmur3FieldMapper.java:137)
so cardinality aggregations can run on pre-hashed values. This is the
canonical x64_128 finalization; only h1 is returned, as a SIGNED 64-bit
int matching the Java long.
"""

from __future__ import annotations

_M = (1 << 64) - 1
_C1 = 0x87C37B91114253D5
_C2 = 0x4CF5AD432745937F


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M


def _fmix(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _M
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _M
    k ^= k >> 33
    return k


def hash128_x64_h1(data: bytes, seed: int = 0) -> int:
    """First 64-bit lane of MurmurHash3 x64_128 as a signed Java long."""
    length = len(data)
    h1 = h2 = seed
    nblocks = length // 16
    for i in range(nblocks):
        k1 = int.from_bytes(data[i * 16:i * 16 + 8], "little")
        k2 = int.from_bytes(data[i * 16 + 8:i * 16 + 16], "little")
        k1 = (k1 * _C1) & _M
        k1 = _rotl(k1, 31)
        k1 = (k1 * _C2) & _M
        h1 ^= k1
        h1 = _rotl(h1, 27)
        h1 = (h1 + h2) & _M
        h1 = (h1 * 5 + 0x52DCE729) & _M
        k2 = (k2 * _C2) & _M
        k2 = _rotl(k2, 33)
        k2 = (k2 * _C1) & _M
        h2 ^= k2
        h2 = _rotl(h2, 31)
        h2 = (h2 + h1) & _M
        h2 = (h2 * 5 + 0x38495AB5) & _M
    tail = data[nblocks * 16:]
    k1 = k2 = 0
    if len(tail) > 8:
        k2 = int.from_bytes(tail[8:].ljust(8, b"\x00"), "little")
        k2 = (k2 * _C2) & _M
        k2 = _rotl(k2, 33)
        k2 = (k2 * _C1) & _M
        h2 ^= k2
    if tail:
        k1 = int.from_bytes(tail[:8].ljust(8, b"\x00"), "little")
        k1 = (k1 * _C1) & _M
        k1 = _rotl(k1, 31)
        k1 = (k1 * _C2) & _M
        h1 ^= k1
    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & _M
    h2 = (h2 + h1) & _M
    h1 = _fmix(h1)
    h2 = _fmix(h2)
    h1 = (h1 + h2) & _M
    return h1 - (1 << 64) if h1 >= (1 << 63) else h1
