from elasticsearch_tpu.utils.hashing import murmur3_hash32

__all__ = ["murmur3_hash32"]
