"""Murmur3 x86_32 — the reference's doc-routing hash.

Doc → shard routing in the reference is
``MathUtils.mod(murmur3(routing_key), num_shards)``
(core/cluster/routing/OperationRouting.java:238-258,
Murmur3HashFunction.java). We implement the same algorithm so routing is
deterministic and documented, and so cross-implementation tests can pin
exact shard assignments.
"""

from __future__ import annotations


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & 0xFFFFFFFF


def _fmix32(h: int) -> int:
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def murmur3_hash32(data: bytes | str, seed: int = 0) -> int:
    """MurmurHash3 x86_32. Returns a signed 32-bit int (Java semantics)."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h1 = seed & 0xFFFFFFFF
    nblocks = len(data) // 4
    for i in range(nblocks):
        k1 = int.from_bytes(data[i * 4:i * 4 + 4], "little")
        k1 = (k1 * c1) & 0xFFFFFFFF
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
        h1 = _rotl32(h1, 13)
        h1 = (h1 * 5 + 0xE6546B64) & 0xFFFFFFFF
    tail = data[nblocks * 4:]
    k1 = 0
    if len(tail) >= 3:
        k1 ^= tail[2] << 16
    if len(tail) >= 2:
        k1 ^= tail[1] << 8
    if len(tail) >= 1:
        k1 ^= tail[0]
        k1 = (k1 * c1) & 0xFFFFFFFF
        k1 = _rotl32(k1, 15)
        k1 = (k1 * c2) & 0xFFFFFFFF
        h1 ^= k1
    h1 ^= len(data)
    h1 = _fmix32(h1)
    return h1 - 0x100000000 if h1 >= 0x80000000 else h1
