"""GeoJSON-ish shape parsing shared by the geo_shape field mapper and the
geo_shape query (ref: core/common/geo/builders/ShapeBuilder.java).

Shapes reduce to a single CLOSED vertex ring (lat/lon lists where the last
vertex repeats the first): point → 1 vertex, envelope → 4, polygon → its
outer ring, circle → a 32-gon. Holes, multi-geometries and linestrings are
not supported (documented simplification — the reference triangulates into
a prefix-tree index; here relations run as exact dense polygon tests on
device, ops/geoshape.py).
"""

from __future__ import annotations

import math

from elasticsearch_tpu.common.errors import QueryParsingError

CIRCLE_SEGMENTS = 32


def parse_shape(shape: dict) -> tuple[list[float], list[float]]:
    """→ (lats, lons) closed ring (last vertex == first; len ≥ 2)."""
    if not isinstance(shape, dict) or "type" not in shape:
        raise QueryParsingError(f"cannot parse shape [{shape!r}]")
    stype = str(shape["type"]).lower()
    coords = shape.get("coordinates")
    if stype == "point":
        lon, lat = float(coords[0]), float(coords[1])
        return [lat, lat], [lon, lon]
    if stype == "envelope":
        # ES order: [[west, north], [east, south]]
        (w, n), (e, s) = coords
        lats = [float(n), float(n), float(s), float(s), float(n)]
        lons = [float(w), float(e), float(e), float(w), float(w)]
        return lats, lons
    if stype == "polygon":
        ring = coords[0]
        if len(coords) > 1:
            raise QueryParsingError(
                "geo_shape polygons with holes are not supported")
        lats = [float(p[1]) for p in ring]
        lons = [float(p[0]) for p in ring]
        if lats[0] != lats[-1] or lons[0] != lons[-1]:
            lats.append(lats[0])
            lons.append(lons[0])
        if len(lats) < 4:
            raise QueryParsingError("polygon needs at least 3 vertices")
        return lats, lons
    if stype == "circle":
        lon, lat = float(coords[0]), float(coords[1])
        from elasticsearch_tpu.search.query_dsl import parse_distance
        radius_m = parse_distance(shape.get("radius", "0m"))
        # meters → degrees (local tangent approximation)
        dlat = radius_m / 111_320.0
        dlon = radius_m / (111_320.0 * max(math.cos(math.radians(lat)),
                                           1e-6))
        lats, lons = [], []
        for i in range(CIRCLE_SEGMENTS + 1):
            a = 2.0 * math.pi * i / CIRCLE_SEGMENTS
            lats.append(lat + dlat * math.sin(a))
            lons.append(lon + dlon * math.cos(a))
        return lats, lons
    raise QueryParsingError(
        f"geo_shape type [{stype}] is not supported "
        f"(point/envelope/polygon/circle)")
