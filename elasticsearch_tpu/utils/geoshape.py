"""GeoJSON-ish shape parsing shared by the geo_shape field mapper and the
geo_shape query (ref: core/common/geo/builders/ShapeBuilder.java,
PolygonBuilder, MultiPolygonBuilder, LineStringBuilder).

Shapes reduce to a MULTI-RING vertex soup: concatenated per-ring closed
(or open, for lines) lat/lon runs plus a per-vertex ring id and a
per-vertex "area" flag. Relations run as exact dense tests on device
(ops/geoshape.py) with global EVEN-ODD parity over area rings — which
makes polygon holes (outer ring + hole rings), multipolygons
(disjunction falls out of parity + per-ring edge tests) and
line/point geometries all exact without member-by-member decomposition:

* polygon with holes → outer ring + hole rings, all area rings; a point
  inside a hole has even crossing parity, i.e. outside the shape;
* multipolygon → every member's rings; a point inside any member has
  odd parity;
* linestring / multilinestring → open runs flagged non-area (their
  edges intersect, but contribute no inside-ness);
* point / multipoint → degenerate 2-vertex rings (zero-length edge:
  boundary contact still registers as intersection);
* envelope → 4-edge ring; circle → a 32-gon ring.
"""

from __future__ import annotations

import math

from elasticsearch_tpu.common.errors import QueryParsingError

CIRCLE_SEGMENTS = 32


def parse_shape_rings(shape: dict
                      ) -> tuple[list[float], list[float], list[int],
                                 list[bool]]:
    """→ (lats, lons, rid, area): concatenated rings, ``rid[i]`` the
    vertex's ring id (edges only exist between same-rid neighbours),
    ``area[i]`` True when the ring encloses area (polygon/envelope/
    circle/point rings; False for linestring runs)."""
    lats: list[float] = []
    lons: list[float] = []
    rid: list[int] = []
    area: list[bool] = []
    next_rid = [0]

    def add_ring(rl: list[float], ro: list[float], is_area: bool,
                 close: bool) -> None:
        rl, ro = list(rl), list(ro)
        if close and (rl[0] != rl[-1] or ro[0] != ro[-1]):
            rl.append(rl[0])
            ro.append(ro[0])
        r = next_rid[0]
        next_rid[0] += 1
        lats.extend(rl)
        lons.extend(ro)
        rid.extend([r] * len(rl))
        area.extend([is_area] * len(rl))

    def walk(node: dict) -> None:
        if not isinstance(node, dict) or "type" not in node:
            raise QueryParsingError(f"cannot parse shape [{node!r}]")
        stype = str(node["type"]).lower()
        coords = node.get("coordinates")
        if stype == "point":
            lon, lat = float(coords[0]), float(coords[1])
            add_ring([lat, lat], [lon, lon], True, False)
        elif stype == "multipoint":
            for p in coords:
                lon, lat = float(p[0]), float(p[1])
                add_ring([lat, lat], [lon, lon], True, False)
        elif stype == "envelope":
            # ES order: [[west, north], [east, south]]
            (w, n), (e, s) = coords
            add_ring([float(n), float(n), float(s), float(s), float(n)],
                     [float(w), float(e), float(e), float(w), float(w)],
                     True, False)
        elif stype == "polygon":
            for ring in coords:          # outer first, then holes —
                if len(ring) < 3:        # even-odd parity handles both
                    raise QueryParsingError(
                        "polygon needs at least 3 vertices")
                add_ring([float(p[1]) for p in ring],
                         [float(p[0]) for p in ring], True, True)
        elif stype == "multipolygon":
            for poly in coords:
                for ring in poly:
                    if len(ring) < 3:
                        raise QueryParsingError(
                            "polygon needs at least 3 vertices")
                    add_ring([float(p[1]) for p in ring],
                             [float(p[0]) for p in ring], True, True)
        elif stype == "linestring":
            if len(coords) < 2:
                raise QueryParsingError(
                    "linestring needs at least 2 vertices")
            add_ring([float(p[1]) for p in coords],
                     [float(p[0]) for p in coords], False, False)
        elif stype == "multilinestring":
            for line in coords:
                if len(line) < 2:
                    raise QueryParsingError(
                        "linestring needs at least 2 vertices")
                add_ring([float(p[1]) for p in line],
                         [float(p[0]) for p in line], False, False)
        elif stype == "circle":
            lon, lat = float(coords[0]), float(coords[1])
            from elasticsearch_tpu.search.query_dsl import parse_distance
            radius_m = parse_distance(node.get("radius", "0m"))
            # meters → degrees (local tangent approximation)
            dlat = radius_m / 111_320.0
            dlon = radius_m / (111_320.0 *
                               max(math.cos(math.radians(lat)), 1e-6))
            rl, ro = [], []
            for i in range(CIRCLE_SEGMENTS + 1):
                a = 2.0 * math.pi * i / CIRCLE_SEGMENTS
                rl.append(lat + dlat * math.sin(a))
                ro.append(lon + dlon * math.cos(a))
            add_ring(rl, ro, True, False)
        elif stype == "geometrycollection":
            for sub in node.get("geometries", []):
                walk(sub)
        else:
            raise QueryParsingError(
                f"geo_shape type [{stype}] is not supported")

    walk(shape)
    if not lats:
        raise QueryParsingError(f"empty shape [{shape!r}]")
    return lats, lons, rid, area


def parse_shape(shape: dict) -> tuple[list[float], list[float]]:
    """Legacy single-ring view: the FIRST ring of the parsed shape
    (kept for callers that predate multi-ring support)."""
    lats, lons, rid, _ = parse_shape_rings(shape)
    n = rid.count(0)
    return lats[:n], lons[:n]
