"""Geohash encoding (ref: core/common/geo/GeoHashUtils.java — base-32
interleaved lat/lon bits; the context suggester's geo contexts and the
geohash_grid aggregation key on these)."""

from __future__ import annotations

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"

#: geohash length → approximate cell size in meters (ES's
#: GeoUtils.geoHashLevelsForPrecision table, coarsest edge)
_CELL_METERS = [None, 5_009_400, 1_252_300, 156_500, 39_100, 4_900,
                1_200, 152.9, 38.2, 4.78, 1.19, 0.149, 0.037]


def geohash_encode(lat: float, lon: float, length: int = 12) -> str:
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    out = []
    bit = 0
    ch = 0
    even = True
    while len(out) < length:
        if even:
            mid = (lon_lo + lon_hi) / 2
            if lon >= mid:
                ch = (ch << 1) | 1
                lon_lo = mid
            else:
                ch <<= 1
                lon_hi = mid
        else:
            mid = (lat_lo + lat_hi) / 2
            if lat >= mid:
                ch = (ch << 1) | 1
                lat_lo = mid
            else:
                ch <<= 1
                lat_hi = mid
        even = not even
        bit += 1
        if bit == 5:
            out.append(_BASE32[ch])
            bit = 0
            ch = 0
    return "".join(out)


def precision_to_length(precision) -> int:
    """'5km' / '100m' / meters → the geohash length whose cells are at
    least that fine (GeoUtils.geoHashLevelsForPrecision)."""
    meters = None
    if isinstance(precision, (int, float)):
        if precision <= 12:              # bare number = geohash length
            return max(1, int(precision))
        meters = float(precision)
    else:
        s = str(precision).strip().lower()
        for suffix, mult in (("km", 1000.0), ("m", 1.0)):
            if s.endswith(suffix):
                meters = float(s[: -len(suffix)]) * mult
                break
        if meters is None:
            return max(1, min(int(float(s)), 12))  # bare geohash length
    for length in range(1, 13):
        if _CELL_METERS[length] <= meters:
            return length
    return 12


def geohash_decode_bbox(gh: str) -> tuple[float, float, float, float]:
    """geohash → (lat_lo, lat_hi, lon_lo, lon_hi) cell bounds."""
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    even = True
    for c in gh:
        ch = _BASE32.index(c)
        for bit in (16, 8, 4, 2, 1):
            if even:
                mid = (lon_lo + lon_hi) / 2
                if ch & bit:
                    lon_lo = mid
                else:
                    lon_hi = mid
            else:
                mid = (lat_lo + lat_hi) / 2
                if ch & bit:
                    lat_lo = mid
                else:
                    lat_hi = mid
            even = not even
    return lat_lo, lat_hi, lon_lo, lon_hi


def geohash_neighbors(gh: str) -> list[str]:
    """The 8 neighboring cells at the same precision (re-encoding the
    centers offset by one cell — robust at edges/poles; duplicates and
    the cell itself are dropped)."""
    lat_lo, lat_hi, lon_lo, lon_hi = geohash_decode_bbox(gh)
    dlat = lat_hi - lat_lo
    dlon = lon_hi - lon_lo
    clat = (lat_lo + lat_hi) / 2
    clon = (lon_lo + lon_hi) / 2
    out = []
    for dy in (-1, 0, 1):
        for dx in (-1, 0, 1):
            if dx == 0 and dy == 0:
                continue
            nlat = clat + dy * dlat
            nlon = clon + dx * dlon
            if not -90.0 <= nlat <= 90.0:
                continue
            nlon = ((nlon + 180.0) % 360.0) - 180.0
            n = geohash_encode(nlat, nlon, len(gh))
            if n != gh and n not in out:
                out.append(n)
    return out
