"""Geohash encoding (ref: core/common/geo/GeoHashUtils.java — base-32
interleaved lat/lon bits; the context suggester's geo contexts and the
geohash_grid aggregation key on these)."""

from __future__ import annotations

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"

#: geohash length → approximate cell size in meters (ES's
#: GeoUtils.geoHashLevelsForPrecision table, coarsest edge)
_CELL_METERS = [None, 5_009_400, 1_252_300, 156_500, 39_100, 4_900,
                1_200, 152.9, 38.2, 4.78, 1.19, 0.149, 0.037]


def geohash_encode(lat: float, lon: float, length: int = 12) -> str:
    lat_lo, lat_hi = -90.0, 90.0
    lon_lo, lon_hi = -180.0, 180.0
    out = []
    bit = 0
    ch = 0
    even = True
    while len(out) < length:
        if even:
            mid = (lon_lo + lon_hi) / 2
            if lon >= mid:
                ch = (ch << 1) | 1
                lon_lo = mid
            else:
                ch <<= 1
                lon_hi = mid
        else:
            mid = (lat_lo + lat_hi) / 2
            if lat >= mid:
                ch = (ch << 1) | 1
                lat_lo = mid
            else:
                ch <<= 1
                lat_hi = mid
        even = not even
        bit += 1
        if bit == 5:
            out.append(_BASE32[ch])
            bit = 0
            ch = 0
    return "".join(out)


def precision_to_length(precision) -> int:
    """'5km' / '100m' / meters → the geohash length whose cells are at
    least that fine (GeoUtils.geoHashLevelsForPrecision)."""
    meters = None
    if isinstance(precision, (int, float)):
        if precision <= 12:              # bare number = geohash length
            return max(1, int(precision))
        meters = float(precision)
    else:
        s = str(precision).strip().lower()
        for suffix, mult in (("km", 1000.0), ("m", 1.0)):
            if s.endswith(suffix):
                meters = float(s[: -len(suffix)]) * mult
                break
        if meters is None:
            return max(1, min(int(float(s)), 12))  # bare geohash length
    for length in range(1, 13):
        if _CELL_METERS[length] <= meters:
            return length
    return 12
