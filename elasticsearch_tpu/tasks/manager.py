"""TaskManager — the node's registry of everything currently running.

Reference: core/tasks/TaskManager.java — every inbound transport request
and every locally-spawned action registers a :class:`Task` with a
cluster-unique id (``node_id:seq``, TaskId.java) and a parent-task link
that propagates on every outgoing RPC, so a search fanning out to N
shards is visible as one coordinating task plus N children across the
cluster. Cancellation is cooperative (CancellableTask.java): cancelling
a task flips a flag the work checks at checkpoint boundaries, and a BAN
on the parent id (TaskManager.setBan) propagates to other nodes so
children registered *after* the cancel are born cancelled. Orphans —
children whose coordinating node left the cluster — are reaped on
node-left events.

Accounting rides the registry: wall time, threadpool queue time
(EsThreadPoolExecutor timing), circuit-breaker bytes attributed to the
task, and phase-level trace spans (query/fetch/reduce) that feed the
response ``took`` breakdown and nodes stats.

The thread-local *current task* is the propagation seam: the transport
layer sets it around handler dispatch, :class:`FixedThreadPool` carries
it across submit boundaries, and ``send_request`` reads it to stamp the
parent-task header on outbound RPCs.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time

from elasticsearch_tpu.common.errors import TaskCancelledError

#: request-dict key carrying the parent task id across the wire — the
#: TransportService strips it before the handler sees the request (the
#: reference writes TaskId into the request envelope; our envelope is
#:  the request dict itself)
TASK_HEADER = "__parent_task_id__"

#: sentinel: register() inherits the parent from the thread-local
#: current task (explicit None means "root task, no parent")
AUTO_PARENT = object()

_tls = threading.local()
#: thread ident → Task, for hot_threads' "what task is this thread
#: running" report (sampled from another thread, hence not thread-local)
_thread_tasks: dict[int, "Task"] = {}


def current_task() -> "Task | None":
    return getattr(_tls, "task", None)


@contextlib.contextmanager
def use_task(task: "Task | None"):
    """Make ``task`` the thread's current task for the duration (no-op
    context when task is None so call sites don't branch)."""
    prev = getattr(_tls, "task", None)
    _tls.task = task
    ident = threading.get_ident()
    if task is not None:
        _thread_tasks[ident] = task
    try:
        yield task
    finally:
        _tls.task = prev
        if prev is not None:
            _thread_tasks[ident] = prev
        else:
            _thread_tasks.pop(ident, None)


def bind_current(fn):
    """Capture the caller's current task AND observability context
    (trace spans, attribution, node override) so ``fn`` runs under them
    on another thread (the context-preserving submit the reference gets
    from ThreadContext.preserveContext)."""
    from elasticsearch_tpu.observability.tracing import bind_context
    fn = bind_context(fn)
    task = current_task()
    if task is None:
        return fn

    def bound(*args, **kwargs):
        with use_task(task):
            return fn(*args, **kwargs)
    return bound


def task_of_thread(ident: int) -> "Task | None":
    """The task a thread is currently running, if any (hot_threads)."""
    return _thread_tasks.get(ident)


def raise_if_cancelled() -> None:
    """Cooperative cancellation checkpoint: raises
    :class:`TaskCancelledError` when the thread's current task (or any
    ancestor registered on this node) has been cancelled."""
    task = current_task()
    if task is not None and task.cancelled:
        raise TaskCancelledError(
            f"task [{task.task_id}] was cancelled "
            f"[{task.cancel_reason or 'unknown'}]")


def note_breaker_bytes(nbytes: int) -> None:
    """Attribute a circuit-breaker reservation to the current task
    (cumulative — the task's total scratch demand, not the live level;
    leak detection stays with the breakers themselves)."""
    task = current_task()
    if task is not None:
        task.breaker_bytes += int(nbytes)


def note_queue_ns(ns: int) -> None:
    """Attribute threadpool queue wait to the current task, and feed
    the per-node ``queue_wait`` latency histogram (_nodes/stats)."""
    task = current_task()
    if task is not None:
        task.queue_ns += int(ns)
    from elasticsearch_tpu.observability import histograms
    histograms.observe_lane("queue_wait", ns / 1e6)


class Task:
    """One unit of running work (Task.java / CancellableTask.java)."""

    __slots__ = ("id", "task_id", "node_id", "action", "description",
                 "parent_task_id", "type", "cancellable", "cancelled",
                 "cancel_reason", "start_time_ms", "_start_ns",
                 "queue_ns", "breaker_bytes", "spans", "deadline",
                 "ban_sent")

    def __init__(self, node_id: str, seq: int, action: str,
                 description: str, parent_task_id: str | None,
                 task_type: str, cancellable: bool):
        self.id = seq
        self.node_id = node_id
        self.task_id = f"{node_id}:{seq}"
        self.action = action
        self.description = description
        self.parent_task_id = parent_task_id
        self.type = task_type                  # "transport" | "direct"
        self.cancellable = cancellable
        self.cancelled = False
        self.cancel_reason: str | None = None
        self.start_time_ms = int(time.time() * 1000)
        self._start_ns = time.monotonic_ns()
        self.queue_ns = 0
        self.breaker_bytes = 0
        #: [(name, took_ms)] — phase trace (query/fetch/reduce)
        self.spans: list[tuple[str, float]] = []
        #: absolute monotonic deadline (search timeout wired through the
        #: task, so per-shard budgets shrink with elapsed wall time)
        self.deadline: float | None = None
        #: a cancel for this task was broadcast as a cluster-wide ban —
        #: unregister must broadcast the ban removal
        self.ban_sent = False

    def running_time_ns(self) -> int:
        return time.monotonic_ns() - self._start_ns

    def add_span(self, name: str, took_ms: float) -> None:
        self.spans.append((name, float(took_ms)))

    def to_dict(self, detailed: bool = True) -> dict:
        out = {
            "node": self.node_id,
            "id": self.id,
            "type": self.type,
            "action": self.action,
            "start_time_in_millis": self.start_time_ms,
            "running_time_in_nanos": self.running_time_ns(),
            "cancellable": self.cancellable,
        }
        if self.cancelled:
            out["cancelled"] = True
        if self.parent_task_id is not None:
            out["parent_task_id"] = self.parent_task_id
        if detailed:
            out["description"] = self.description
            out["queue_time_in_nanos"] = self.queue_ns
            out["breaker_bytes"] = self.breaker_bytes
            if self.spans:
                out["phases"] = [{"name": n, "took_ms": round(ms, 3)}
                                 for n, ms in self.spans]
        return out


class TaskManager:
    """Per-node task registry + ban table (TaskManager.java)."""

    def __init__(self, node_id: str, node_name: str = ""):
        self.node_id = node_id
        self.node_name = node_name
        self._seq = itertools.count(1)
        self._tasks: dict[int, Task] = {}
        #: banned parent task id → reason: children registering under a
        #: banned parent are born cancelled (setBan semantics)
        self._bans: dict[str, str] = {}
        self._lock = threading.Lock()
        self.total_registered = 0
        self.total_cancelled = 0
        #: phase name → {"count", "time_in_millis"} rollup of completed
        #: tasks' spans (nodes stats)
        self.phase_totals: dict[str, dict] = {}
        #: set by the node: callable(parent_task_id, ban: bool, reason)
        #: broadcasting a ban (or its removal) to the rest of the cluster
        self.ban_broadcaster = None

    # ---- registry ----------------------------------------------------------

    def register(self, action: str, description: str = "",
                 parent_task_id=AUTO_PARENT, task_type: str = "direct",
                 cancellable: bool = True) -> Task:
        if parent_task_id is AUTO_PARENT:
            cur = current_task()
            parent_task_id = cur.task_id if cur is not None else None
        task = Task(self.node_id, next(self._seq), action, description,
                    parent_task_id, task_type, cancellable)
        with self._lock:
            self._tasks[task.id] = task
            self.total_registered += 1
            if parent_task_id is not None and parent_task_id in self._bans:
                # born under a ban: cancelled before it runs a step
                task.cancelled = True
                task.cancel_reason = self._bans[parent_task_id]
                self.total_cancelled += 1
        return task

    def unregister(self, task: Task | None) -> None:
        if task is None:
            return
        with self._lock:
            self._tasks.pop(task.id, None)
            for name, ms in task.spans:
                tot = self.phase_totals.setdefault(
                    name, {"count": 0, "time_in_millis": 0})
                tot["count"] += 1
                tot["time_in_millis"] += int(ms)
        if task.ban_sent and self.ban_broadcaster is not None:
            # the parent finished: lift the cluster-wide ban so the id
            # space can't accumulate dead bans (TaskManager.removeBan)
            try:
                self.ban_broadcaster(task.task_id, False,
                                     task.cancel_reason or "")
            except Exception:       # noqa: BLE001 — best-effort cleanup
                pass

    def get(self, task_id: str) -> Task | None:
        """Lookup by full "node:seq" id (local tasks only)."""
        node, _, seq = str(task_id).rpartition(":")
        if node != self.node_id:
            return None
        try:
            return self._tasks.get(int(seq))
        except ValueError:
            return None

    def list_tasks(self, actions: list[str] | None = None,
                   parent_task_id: str | None = None,
                   detailed: bool = True) -> dict:
        """→ {task_id: task dict} for tasks matching the filters
        (ListTasksRequest match semantics: action patterns support a
        trailing ``*`` wildcard)."""
        import fnmatch
        with self._lock:
            snapshot = list(self._tasks.values())
        out = {}
        for t in snapshot:
            if parent_task_id is not None and \
                    t.parent_task_id != parent_task_id:
                continue
            if actions and not any(fnmatch.fnmatch(t.action, pat)
                                   for pat in actions):
                continue
            out[t.task_id] = t.to_dict(detailed)
        return out

    def active_count(self) -> int:
        with self._lock:
            return len(self._tasks)

    # ---- cancellation ------------------------------------------------------

    def cancel(self, task: Task, reason: str) -> None:
        """Mark a task (and its LOCAL descendants) cancelled. Remote
        descendants are handled by the ban broadcast (node layer)."""
        with self._lock:
            self._cancel_locked(task, reason)

    def _cancel_locked(self, task: Task, reason: str) -> None:
        if not task.cancelled:
            task.cancelled = True
            task.cancel_reason = reason
            self.total_cancelled += 1
        # local descendants: children registered on THIS node under the
        # cancelled task, recursively
        for child in [t for t in self._tasks.values()
                      if t.parent_task_id == task.task_id]:
            self._cancel_locked(child, reason)

    def set_ban(self, parent_task_id: str, reason: str) -> int:
        """Ban a parent id: cancel every current task under it and mark
        the id so future registrations are born cancelled. → number of
        tasks cancelled now."""
        with self._lock:
            self._bans[parent_task_id] = reason
            victims = [t for t in self._tasks.values()
                       if t.parent_task_id == parent_task_id]
            for t in victims:
                self._cancel_locked(t, reason)
            return len(victims)

    def remove_ban(self, parent_task_id: str) -> None:
        with self._lock:
            self._bans.pop(parent_task_id, None)

    def bans(self) -> dict:
        with self._lock:
            return dict(self._bans)

    def reap_node_left(self, node_id: str) -> int:
        """A node left the cluster: cancel every task parented on it
        (orphaned children — their coordinator can neither collect nor
        cancel them) and drop bans it originated. Cooperative: the
        running work aborts at its next checkpoint and unregisters
        through the normal completion path, releasing breaker bytes.
        → tasks cancelled."""
        prefix = f"{node_id}:"
        with self._lock:
            victims = [t for t in self._tasks.values()
                       if (t.parent_task_id or "").startswith(prefix)]
            for t in victims:
                self._cancel_locked(
                    t, f"coordinating node [{node_id}] left the cluster")
            for banned in [b for b in self._bans
                           if b.startswith(prefix)]:
                del self._bans[banned]
            return len(victims)

    # ---- stats -------------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "active_count": len(self._tasks),
                "total_registered": self.total_registered,
                "total_cancelled": self.total_cancelled,
                "bans": len(self._bans),
                "phases": {k: dict(v)
                           for k, v in sorted(self.phase_totals.items())},
            }
