"""Task management (core/tasks/): cluster-wide task registry,
cross-node cancellation bans, and per-request accounting/tracing."""

from elasticsearch_tpu.tasks.manager import (
    AUTO_PARENT, TASK_HEADER, Task, TaskManager, bind_current,
    current_task, note_breaker_bytes, note_queue_ns, raise_if_cancelled,
    task_of_thread, use_task)

__all__ = [
    "AUTO_PARENT", "TASK_HEADER", "Task", "TaskManager", "bind_current",
    "current_task", "note_breaker_bytes", "note_queue_ns",
    "raise_if_cancelled", "task_of_thread", "use_task",
]
