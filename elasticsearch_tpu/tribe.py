"""Tribe node — a federated read view over multiple clusters.

Reference: core/tribe/TribeService.java — the tribe node runs one inner
client node per configured tribe, merges every member cluster's state
into its own (indices tagged with their tribe name), and serves reads by
routing each index to the cluster that owns it; writes to tribe-managed
indices are rejected (the tribe's master is a local no-op).
"""

from __future__ import annotations

import threading

from elasticsearch_tpu.common.errors import (
    ElasticsearchTpuError, IndexNotFoundError)


def _total(t):
    """hits.total is a bare count (2.x REST shape); accept the object
    form too for inner clients that may be version-skewed."""
    return t["value"] if isinstance(t, dict) else int(t)

class TribeWriteError(ElasticsearchTpuError):
    status = 400
    error_type = "illegal_argument_exception"


class TribeService:
    def __init__(self, node, members: dict):
        """`members`: {tribe_name: hub | (hub, cluster_name)} — the
        member cluster's transport hub and its cluster.name (the
        reference's tribe.<name>.cluster.name setting). One inner CLIENT
        node (no data, no master) joins each member cluster; on conflicts
        the FIRST tribe to publish an index name wins (the reference's
        `tribe.on_conflict: any` default)."""
        from elasticsearch_tpu.node import Node
        self.node = node
        self.members: dict[str, Node] = {}
        self._index_owner: dict[str, str] = {}
        self._lock = threading.Lock()
        for name, spec in members.items():
            hub, cluster_name = spec if isinstance(spec, tuple) \
                else (spec, "elasticsearch-tpu")
            inner = Node({"node.data": "false", "node.master": "false",
                          "cluster.name": cluster_name,
                          "node.name": f"{node.node_name}/{name}"},
                         data_path=node.data_path / "tribe" / name,
                         transport_hub=hub)
            inner.start()
            self.members[name] = inner
            inner.cluster_service.add_listener(
                lambda old, new, _t=name: self._member_changed(_t, new))
            self._member_changed(name, inner.cluster_service.state())

    # ---- merged view -------------------------------------------------------

    def _member_changed(self, tribe: str, state) -> None:
        with self._lock:
            for idx in state.indices:
                self._index_owner.setdefault(idx, tribe)
            # drop indices the owning tribe no longer has
            for idx in [i for i, t in self._index_owner.items()
                        if t == tribe and i not in state.indices]:
                del self._index_owner[idx]

    def merged_indices(self) -> dict:
        """{index: {tribe, metadata}} across members."""
        out = {}
        with self._lock:
            owners = dict(self._index_owner)
        for idx, tribe in owners.items():
            meta = self.members[tribe].cluster_service.state() \
                .indices.get(idx)
            if meta is not None:
                out[idx] = {"tribe": tribe, "meta": meta}
        return out

    def owner_of(self, index: str):
        with self._lock:
            tribe = self._index_owner.get(index)
        if tribe is None:
            raise IndexNotFoundError(index)
        return self.members[tribe]

    # ---- federated reads ---------------------------------------------------

    def search(self, index_expr: str, body: dict | None = None) -> dict:
        """Scatter the search to every owning member cluster and merge
        hits by score (the tribe coordinator reduce)."""
        merged = self.merged_indices()
        import fnmatch
        targets: dict[str, list[str]] = {}
        parts = (index_expr or "_all").split(",")
        for idx, info in merged.items():
            if any(p in ("_all", "*") or fnmatch.fnmatch(idx, p)
                   or p == idx for p in parts):
                targets.setdefault(info["tribe"], []).append(idx)
        if not targets:
            raise IndexNotFoundError(index_expr)
        if len(targets) == 1:
            ((t, idxs),) = targets.items()
            return self.members[t].search(",".join(idxs), dict(body or {}))
        # cross-cluster pagination: every member returns its global-window
        # candidates (from=0, size=from+size); the offset applies AFTER
        # the merged sort (the same window discipline as the shard-level
        # SearchPhaseController.sortDocs)
        from_ = int((body or {}).get("from", 0))
        size = int((body or {}).get("size", 10))
        member_body = {**(body or {}), "from": 0, "size": from_ + size}
        responses = [
            self.members[t].search(",".join(idxs), member_body)
            for t, idxs in sorted(targets.items())]
        hits = [h for r in responses for h in r["hits"]["hits"]]
        sort_spec = (body or {}).get("sort")
        if sort_spec and any(h.get("sort") is not None for h in hits):
            # field sort: merge by the members' sort keys, honouring each
            # field's order (the coordinator reduce over sort values)
            specs = sort_spec if isinstance(sort_spec, list) else [sort_spec]
            descs = []
            for sp in specs:
                if isinstance(sp, dict):
                    (fname, opts), = sp.items()
                    order = opts.get("order", "asc") \
                        if isinstance(opts, dict) else opts
                else:
                    fname, order = sp, ("desc" if sp == "_score" else "asc")
                descs.append(str(order) == "desc")

            import functools

            def cmp(a, b):
                va, vb = a.get("sort") or [], b.get("sort") or []
                for i, desc in enumerate(descs):
                    x = va[i] if i < len(va) else None
                    y = vb[i] if i < len(vb) else None
                    if x == y:
                        continue
                    if x is None:        # missing sorts last (ES default)
                        return 1
                    if y is None:
                        return -1
                    less = x < y
                    return (1 if less else -1) if desc \
                        else (-1 if less else 1)
                return 0
            hits.sort(key=functools.cmp_to_key(cmp))
        else:
            hits.sort(key=lambda h: -(h.get("_score") or 0.0))
        scores = [h["_score"] for h in hits
                  if h.get("_score") is not None]
        max_score = max(scores) if scores else None
        hits = hits[from_:from_ + size]
        total = sum(_total(r["hits"]["total"]) for r in responses)
        return {
            "took": max(r.get("took", 0) for r in responses),
            "timed_out": any(r.get("timed_out") for r in responses),
            "_shards": {
                "total": sum(r["_shards"]["total"] for r in responses),
                "successful": sum(r["_shards"]["successful"]
                                  for r in responses),
                "failed": sum(r["_shards"].get("failed", 0)
                              for r in responses)},
            "hits": {"total": total,
                     "max_score": max_score,
                     "hits": hits}}

    def get_doc(self, index: str, doc_id: str, **kw) -> dict:
        return self.owner_of(index).get_doc(index, doc_id, **kw)

    def write_blocked(self, index: str) -> None:
        raise TribeWriteError(
            f"tribe node cannot write to tribe-managed index [{index}]")

    def close(self) -> None:
        for inner in self.members.values():
            inner.close()
