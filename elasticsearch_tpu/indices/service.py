"""IndicesService / IndexService — node-level index containers.

Reference: core/indices/IndicesService.java creates a per-index injector and
per-shard IndexShard instances; IndicesClusterStateService
(core/indices/cluster/IndicesClusterStateService.java:71,140,171-251)
reconciles every published cluster state against local shards: create
indices/shards newly assigned here, remove ones no longer local, apply
mapping updates, and report INITIALIZING→STARTED to the master
(ShardStateAction analog via the `on_shard_started` callback).

Metadata mutations (create/delete index, mappings, aliases) are master-side
state updates (MetaDataCreateIndexService / MetaDataMappingService) that end
with an AllocationService.reroute so new shards get assigned.
"""

from __future__ import annotations

import json
import shutil
import threading
import time
from dataclasses import replace
import uuid
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.cluster.allocation import AllocationService
from elasticsearch_tpu.cluster.routing import OperationRouting
from elasticsearch_tpu.cluster.state import (
    ClusterState, IndexMetadata, ShardRouting, ShardRoutingState)
from elasticsearch_tpu.common.errors import (
    IndexAlreadyExistsError, IndexNotFoundError, IllegalArgumentError)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapping import MapperService


def _normalize_index_settings(raw: dict) -> dict:
    """Create-index bodies accept both `number_of_shards` and
    `index.number_of_shards` — the reference prefixes bare keys with
    `index.` (IndexMetaData settings normalization)."""
    flat = dict(Settings(raw))
    return {k if k.startswith("index.") else f"index.{k}": v
            for k, v in flat.items()}


def normalize_alias(spec: dict | None) -> dict:
    """Alias body → stored AliasMetaData shape; `routing` expands to both
    index_routing and search_routing (ref: AliasMetaData.Builder)."""
    spec = spec or {}
    meta = {}
    if spec.get("filter") is not None:
        meta["filter"] = spec["filter"]
    ir = spec.get("index_routing", spec.get("indexRouting",
                                            spec.get("routing")))
    sr = spec.get("search_routing", spec.get("searchRouting",
                                             spec.get("routing")))
    if ir is not None:
        meta["index_routing"] = str(ir)
    if sr is not None:
        meta["search_routing"] = str(sr)
    return meta


def _plane_breaker_stats() -> dict:
    """The node's plane-breaker document for _stats sections (lazy
    import — jit_exec pulls jax)."""
    from elasticsearch_tpu.search import jit_exec
    return jit_exec.plane_breaker.stats()


def _impact_lane_stats(index_name: str) -> dict:
    """One index's impact-lane rollup for _stats (lazy import)."""
    from elasticsearch_tpu.search import jit_exec
    return jit_exec.impact_index_stats(index_name)


def _knn_lane_stats(index_name: str) -> dict:
    """One index's knn-lane rollup for _stats (lazy import)."""
    from elasticsearch_tpu.search import jit_exec
    return jit_exec.knn_index_stats(index_name)


class ShardNotLocalError(Exception):
    """The target shard copy lives on another node — the action layer must
    route the operation over the transport."""

    def __init__(self, index: str, shard: int):
        super().__init__(f"shard [{index}][{shard}] is not on this node")
        self.index = index
        self.shard = shard


class IndexService:
    """Per-index container: mapper service + one engine per LOCAL shard."""

    def __init__(self, meta: IndexMetadata, path: Path,
                 local_shards: list[int] | None = None,
                 breaker_service=None, merge_submit=None,
                 on_engine_failure=None, disk_fault_lookup=None,
                 reader_swap_lookup=None, request_cache_lookup=None):
        self.merge_submit = merge_submit
        # reader_swap_lookup() → callable(index_name) | None: resolved at
        # FIRE time (the node wires its hook after boot-time reconcile
        # already created indices) — engine reader swaps notify it so the
        # collective plane can pipeline its next-generation pack
        self.reader_swap_lookup = reader_swap_lookup
        # request_cache_lookup() → ShardRequestCache | None: the node's
        # shard request cache, read by stats() for the per-index
        # request_cache section
        self.request_cache_lookup = request_cache_lookup
        # engine self-fail report: on_engine_failure(index, shard, reason)
        # — IndicesService turns it into a shard-failed to the master
        self.on_engine_failure = on_engine_failure
        # node-level disk-fault injection (testing_disruption.
        # DiskFaultScheme): newly created engines pick up the hook so a
        # "bad disk" survives engine recreation until the scheme heals it
        self.disk_fault_lookup = disk_fault_lookup
        self.name = meta.name
        self.meta = meta
        self.path = path
        index_settings = Settings(meta.settings)
        self.index_settings = index_settings
        self.analysis = AnalysisRegistry(index_settings)
        self.mapper_service = MapperService(self.analysis)
        # index-default similarity (SimilarityModule: the `default` named
        # similarity applies to fields without an explicit one)
        self.mapper_service.default_similarity = index_settings.get(
            "index.similarity.default.type")
        for type_name, mapping in (meta.mappings or {}).items():
            self.mapper_service.merge(type_name, mapping)
        from elasticsearch_tpu.index.slowlog import (
            IndexingSlowLog, SearchSlowLog)
        self.search_slow_log = SearchSlowLog(meta.name, index_settings)
        self.indexing_slow_log = IndexingSlowLog(meta.name, index_settings)
        self.breaker_service = breaker_service
        # per-index search stats incl. request groups (ref:
        # core/index/search/stats/ShardSearchStats.java:36 — _all bucket
        # plus one bucket per `stats` group named by the request)
        self.search_stats = {"query_total": 0, "query_time_ms": 0.0,
                             "fetch_total": 0, "fetch_time_ms": 0.0,
                             "groups": {}}
        # collective-plane admission accounting: queries served by the
        # one-program mesh path vs fallbacks to the RPC fan-out, by
        # reason — the observability the default flip ships with
        self.plane_stats: dict = {"served": 0, "fallback": {}}
        # impact-ordered lane opt-in (`index.search.impact_plane`):
        # registers this index's quantized-impact config with the
        # compiled execution layer; absent/false leaves the exact
        # scorer as the only scorer
        from elasticsearch_tpu.search import jit_exec as _jit_exec
        _jit_exec.configure_impact_plane(self.name, self.index_settings)
        # knn-lane config (`index.knn.quantization`, hybrid fusion
        # knobs): always registered — the top-level `knn` search
        # section is the lane's opt-in, the settings only tune it
        _jit_exec.configure_knn_plane(self.name, self.index_settings)
        # per-type indexing counters (ShardIndexingService typeStats)
        self.indexing_types: dict[str, int] = {}
        self.engines: dict[int, Engine] = {}
        if local_shards is None:
            local_shards = list(range(meta.number_of_shards))
        for sid in local_shards:
            self.add_local_shard(sid)

    # ---- local shard management -------------------------------------------

    def add_local_shard(self, sid: int) -> Engine:
        if sid not in self.engines:
            # engine-factory seam (IndexModule.engineFactoryImpl,
            # core/index/IndexModule.java:37): index.engine.type selects
            # the asserting test wrapper (MockEngineFactory analog)
            from elasticsearch_tpu.index.asserting import engine_class_for
            engine_cls = engine_class_for(self.index_settings)
            engine = engine_cls(self.path / str(sid), self.mapper_service,
                                self.index_settings)
            engine.indexing_slow_log = self.indexing_slow_log
            engine.breaker_service = self.breaker_service
            engine.merge_executor = self.merge_submit
            if self.on_engine_failure is not None:
                engine.on_failure = (
                    lambda reason, _n=self.name, _s=sid:
                    self.on_engine_failure(_n, _s, reason))
            fault = (self.disk_fault_lookup()
                     if self.disk_fault_lookup is not None else None)
            if fault is not None:
                engine.disk_fault = fault
                engine.translog.fault_hook = fault
            if self.reader_swap_lookup is not None:
                # late-bound: the node wires the actual hook (the plane's
                # double-buffered rebuild scheduler) after boot reconcile
                def _on_swap(_n=self.name, _lk=self.reader_swap_lookup):
                    hook = _lk()
                    if hook is not None:
                        hook(_n)
                engine.reader_swap_listeners.append(_on_swap)
            self.engines[sid] = engine
        return self.engines[sid]

    def apply_settings(self, meta: IndexMetadata) -> None:
        """Dynamic settings landed in new metadata (IndexSettingsService
        analog): refresh the pieces that read them."""
        self.index_settings = Settings(meta.settings)
        self.search_slow_log.update_settings(self.index_settings)
        self.indexing_slow_log.update_settings(self.index_settings)

    def remove_local_shard(self, sid: int, delete_files: bool = False) -> None:
        engine = self.engines.pop(sid, None)
        if engine is not None:
            engine.close()
        if delete_files:
            shutil.rmtree(self.path / str(sid), ignore_errors=True)

    @property
    def shard_engines(self) -> list[Engine]:
        """Local engines in shard order (search iterates these)."""
        return [self.engines[sid] for sid in sorted(self.engines)]

    def shard_id_for(self, doc_id: str, routing: str | None = None) -> int:
        return OperationRouting.shard_id(doc_id, self.meta.number_of_shards,
                                         routing)

    def shard_for(self, doc_id: str, routing: str | None = None) -> Engine:
        sid = self.shard_id_for(doc_id, routing)
        engine = self.engines.get(sid)
        if engine is None:
            raise ShardNotLocalError(self.name, sid)
        return engine

    def engine(self, sid: int) -> Engine:
        e = self.engines.get(sid)
        if e is None:
            raise ShardNotLocalError(self.name, sid)
        return e

    def refresh(self):
        for e in self.shard_engines:
            e.refresh()
        self.run_warmers()

    def run_warmers(self) -> int:
        """Execute registered warmers against the fresh readers (ref:
        core/index/warmer/ + IndicesWarmer — warmers run whenever a new
        searcher opens). Here a warmer run packs the new device reader
        and compiles/caches the warmer query's program, so the first real
        search after a refresh hits warm caches. → warmers executed."""
        warmers = getattr(self.meta, "warmers", None)
        if not warmers:
            return 0
        from elasticsearch_tpu.index.device_reader import device_reader_for
        from elasticsearch_tpu.search.phase import (
            ShardSearcher, parse_search_request)
        ran = 0
        for sid, engine in list(self.engines.items()):
            try:
                searcher = ShardSearcher(sid, device_reader_for(engine),
                                         self.mapper_service,
                                         index_name=self.name)
            except Exception:            # noqa: BLE001 — engine closing
                continue
            for spec in warmers.values():
                try:                     # one bad warmer must not stop
                    source = spec.get("source", spec) or {}
                    searcher.query_phase(parse_search_request(source))
                    ran += 1
                except Exception:        # noqa: BLE001 — warmers must
                    continue             # never fail a refresh
        return ran

    def flush(self):
        for e in self.shard_engines:
            e.flush()

    def force_merge(self, max_num_segments: int = 1):
        for e in self.shard_engines:
            e.force_merge(max_num_segments)

    def num_docs(self) -> int:
        return sum(e.num_docs for e in self.shard_engines)

    def _query_cache_stats(self) -> dict:
        """Filter-cache counters: the live reader's counts plus the
        CUMULATIVE tally engines carry across refreshes (ES cache stats
        never reset on a reader swap)."""
        out = {"memory_size_in_bytes": 0, "evictions": 0,
               "hit_count": 0, "miss_count": 0}
        for e in self.shard_engines:
            baseline = getattr(e, "_filter_cache_carry", None)
            if baseline:
                for k in ("hit_count", "miss_count", "evictions"):
                    out[k] += baseline.get(k, 0)
            reader = getattr(e, "_device_reader_cache", None)
            if reader is None:
                continue
            st = getattr(reader, "_filter_cache_stats", None)
            if st:
                out["hit_count"] += st["hit_count"]
                out["miss_count"] += st["miss_count"]
                out["evictions"] += st["evictions"]
            cache = getattr(reader, "_filter_mask_cache", None)
            lock = getattr(reader, "_filter_cache_lock", None)
            if cache and lock is not None:
                # snapshot under the cache's own lock — a concurrent
                # search may insert/evict mid-iteration (_filter_masks_np
                # always creates the lock before the cache)
                with lock:
                    masks = list(cache.values())
                out["memory_size_in_bytes"] += sum(m.nbytes for m in masks)
        return out

    def _request_cache_stats(self) -> dict:
        """Real per-index shard-request-cache counters: the node-level
        ShardRequestCache keys entries by engine uuid, so this index's
        section sums exactly its own engines' hits/misses/evictions and
        live entry bytes (previously hardcoded zeros)."""
        cache = (self.request_cache_lookup()
                 if self.request_cache_lookup is not None else None)
        if cache is None:
            return {"memory_size_in_bytes": 0, "evictions": 0,
                    "hit_count": 0, "miss_count": 0}
        return cache.stats_for(
            e.engine_uuid for e in self.shard_engines)

    def note_plane_served(self, queries: int = 1) -> None:
        """`queries` searches answered by the collective plane (one mesh
        dispatch may serve a whole msearch batch)."""
        self.plane_stats["served"] += queries

    def note_plane_fallback(self, reason: str) -> None:
        """One plane admission attempt that fell back to the RPC fan-out
        (reasons: ineligible_shape / parse_error / refresh_race /
        device_error / not_local)."""
        fb = self.plane_stats["fallback"]
        fb[reason] = fb.get(reason, 0) + 1

    def note_search(self, groups, query_ms: float,
                    fetch_ms: float = 0.0) -> None:
        """One completed shard search (ShardSearchStats.onQueryPhase)."""
        buckets = [self.search_stats]
        for g in groups or []:
            buckets.append(self.search_stats["groups"].setdefault(
                str(g), {"query_total": 0, "query_time_ms": 0.0,
                         "fetch_total": 0, "fetch_time_ms": 0.0}))
        for b in buckets:
            b["query_total"] += 1
            b["query_time_ms"] += query_ms
            b["fetch_total"] += 1
            b["fetch_time_ms"] += fetch_ms

    def _percolate_stats(self) -> dict:
        """The 2.x percolate stats section plus the registry counters the
        batched data plane ships with (same pattern as search.
        collective_plane): ops/time, registered query count, and the
        persistent-registry maintenance counters that prove repeated
        percolates rebuild nothing."""
        from elasticsearch_tpu.search.percolator import registry_stats
        st = registry_stats(self.name)
        base = {"total": 0, "time_in_millis": 0, "current": 0,
                "queries": len(getattr(self.meta, "percolators", {}) or {}),
                "memory_size_in_bytes": -1}
        if st is None:
            return base
        base.update(total=st["count"], time_in_millis=int(st["time_ms"]),
                    queries=st["registered"])
        base["registry"] = {k: st[k] for k in (
            "builds", "syncs", "adds", "removes", "bucket_invalidations",
            "mapper_rebuilds", "shape_buckets", "fused_queries",
            "fallback_queries", "breaker_skips")}
        # compiled-lane cache counters (node-global — the program cache is
        # shared across indices, like indices.jit in _nodes/stats)
        from elasticsearch_tpu.search import jit_exec
        js = jit_exec.cache_stats()
        base["registry"]["program_hits"] = js["percolate_program_hits"]
        base["registry"]["program_misses"] = js["percolate_program_misses"]
        return base

    def stats(self) -> dict:
        agg = {"index_total": 0, "delete_total": 0, "refresh_total": 0,
               "flush_total": 0, "merge_total": 0, "index_time_ms": 0.0}
        segs = []
        for e in self.shard_engines:
            s = e.stats
            agg["index_total"] += s.index_total
            agg["delete_total"] += s.delete_total
            agg["refresh_total"] += s.refresh_total
            agg["flush_total"] += s.flush_total
            agg["merge_total"] += s.merge_total
            agg["index_time_ms"] += s.index_time_ms
            segs.extend(e.segment_stats())
        mem = sum(s["memory_bytes"] for s in segs)
        # completion-field memory: ordinal columns of completion-mapped
        # fields (the FST-size analog the stats API reports)
        completion_bytes = 0
        completion_fields = [
            name for dm in self.mapper_service.mappers.values()
            for name, fm in dm.mappers.items()
            if getattr(fm, "type", None) == "completion"]
        if completion_fields:
            for e in self.shard_engines:
                for seg in e.acquire_searcher().segments:
                    for f in completion_fields:
                        k = seg.keyword_fields.get(f)
                        if k is not None:
                            completion_bytes += k.ords.nbytes
        translog_ops = 0
        translog_bytes = 0
        for e in self.shard_engines:
            try:
                tstats = e.translog.stats()
                translog_ops += tstats.get("operations", 0)
                translog_bytes += tstats.get("size_in_bytes", 0)
            except Exception:                # noqa: BLE001 — optional
                pass
        # the full 2.x section set (RestIndicesStatsAction /
        # CommonStatsFlags): zero-valued sections still render so metric
        # filtering and is_true assertions see the reference shape
        return {
            "docs": {"count": self.num_docs(), "deleted": 0},
            "store": {"size_in_bytes": mem, "throttle_time_in_millis": 0},
            "indexing": {"index_total": agg["index_total"],
                         "index_time_in_millis": int(agg["index_time_ms"]),
                         "delete_total": agg["delete_total"],
                         "is_throttled": False,
                         "throttle_time_in_millis": 0,
                         "types": {
                             t: {"index_total": n,
                                 "index_time_in_millis": 0,
                                 "index_current": 0,
                                 "delete_total": 0,
                                 "delete_time_in_millis": 0,
                                 "delete_current": 0}
                             for t, n in self.indexing_types.items()}},
            "get": {"total": 0, "time_in_millis": 0},
            "search": {
                "open_contexts": 0,
                "query_total": self.search_stats["query_total"],
                "query_time_in_millis":
                    int(self.search_stats["query_time_ms"]),
                "query_current": 0,
                "fetch_total": self.search_stats["fetch_total"],
                "fetch_time_in_millis":
                    int(self.search_stats["fetch_time_ms"]),
                "fetch_current": 0,
                "collective_plane": {
                    "served": self.plane_stats["served"],
                    "fallback": dict(self.plane_stats["fallback"]),
                    "fallback_total":
                        sum(self.plane_stats["fallback"].values()),
                    # accelerator-fault tolerance: is this index's plane
                    # marked degraded (background pack builds exhausted
                    # their retries — searches serve the previous
                    # generation / fan-out), plus the node's plane
                    # breaker (state, trips, probes — shared across
                    # indices like the device it guards)
                    "degraded":
                        bool(self.plane_stats.get("degraded", False)),
                    "breaker": _plane_breaker_stats(),
                    # incremental data-layer traffic attributed to THIS
                    # index's pack builds (bytes uploaded vs reused,
                    # refresh classification) — the per-index view of
                    # jit_exec's node-wide data_layer counters
                    "data_layer": dict(
                        self.plane_stats.get("data_layer", {}))},
                # impact-ordered lane: admissions and block-sweep work
                # attributed to THIS index (skip_ratio ≫ 0 is the
                # per-index sublinearity evidence without the profiler)
                "impact": _impact_lane_stats(self.name),
                # dense/late-interaction lane: compiled-lane admissions,
                # hybrid fusion dispatches (reconciles with the hybrid
                # request count — one dispatch per request), MaxSim
                # dispatches over rank_vectors, attributed to THIS index
                "knn": _knn_lane_stats(self.name),
                "groups": {
                    g: {"query_total": b["query_total"],
                        "query_time_in_millis": int(b["query_time_ms"]),
                        "query_current": 0,
                        "fetch_total": b["fetch_total"],
                        "fetch_time_in_millis": int(b["fetch_time_ms"]),
                        "fetch_current": 0}
                    for g, b in self.search_stats["groups"].items()}},
            "merges": {"total": agg["merge_total"],
                       "total_time_in_millis": 0, "current": 0},
            "refresh": {"total": agg["refresh_total"],
                        "total_time_in_millis": 0},
            "flush": {"total": agg["flush_total"],
                      "total_time_in_millis": 0},
            "warmer": {"current": 0, "total": 0, "total_time_in_millis": 0},
            "query_cache": self._query_cache_stats(),
            "filter_cache": {"memory_size_in_bytes": 0, "evictions": 0},
            "fielddata": {"memory_size_in_bytes": mem, "evictions": 0},
            "completion": {"size_in_bytes": completion_bytes},
            "segments": {"count": len(segs), "memory_in_bytes": mem},
            "translog": {"operations": translog_ops,
                         "size_in_bytes": translog_bytes},
            "suggest": {"total": 0, "time_in_millis": 0},
            "percolate": self._percolate_stats(),
            "request_cache": self._request_cache_stats(),
            "recovery": {"current_as_source": 0, "current_as_target": 0},
        }

    def close(self):
        for e in self.shard_engines:
            e.close()
        # return the collective-plane pack's breaker reservation (set by
        # SearchActions._mesh_searcher_for) — dropping the index must
        # not strand fielddata budget. The charge is one-shot: the
        # engine close listeners above normally fired it already; this
        # covers packs whose engines were removed earlier.
        cached = self.__dict__.pop("_mesh_cache", None)
        if cached is not None:
            charge = getattr(cached[1], "_pack_charge", None)
            if charge is not None:
                charge.release()


class IndicesService:
    def __init__(self, data_path: Path, cluster_service, node_id: str,
                 allocation: AllocationService | None = None):
        self.data_path = Path(data_path)
        self.cluster_service = cluster_service
        self.node_id = node_id
        self.allocation = allocation or AllocationService()
        self.indices: dict[str, IndexService] = {}
        # hierarchical memory accounting (HierarchyCircuitBreakerService);
        # wired by the Node before any index exists
        self.breaker_service = None
        # node-level disk-fault injection hook (testing_disruption.
        # DiskFaultScheme); newly created engines inherit it
        self.disk_fault = None
        # background merges: the Node wires this to its "merge" thread
        # pool; None runs merges inline at refresh (deterministic tests)
        self.merge_submit = None
        # reader-swap hook (Node → SearchActions.schedule_plane_rebuild):
        # engine refreshes/merges notify it with the index name so the
        # collective plane pipelines its next-generation device pack off
        # the query hot path; late-bound via lookup so indices created
        # during boot reconcile (before the node wires it) still fire
        self.reader_swap_hook = None
        # the node's ShardRequestCache (per-index request_cache stats)
        self.request_cache = None
        # Master forwarding seam (TransportMasterNodeAction.java:50): when
        # set by the Node, metadata mutations on a non-master route to the
        # elected master; signature (action, request, local_fn) → result.
        self.master_executor = None
        # allocation ids this node has already reported as started
        self._reported_started: set[str] = set()
        # allocation_id → ("started", None) | ("failed", reason): what we
        # last told the master, so a report LOST to a partition can be
        # re-sent when a later state still shows the shard INITIALIZING
        # (the reference re-sends shardStarted on every clusterChanged
        # where the master's view lags, IndicesClusterStateService)
        self._report_outcome: dict[str, tuple[str, str | None]] = {}
        # Node wires this to the ShardStateAction path:
        # on_shard_started(shard_routing) → master applies started
        self.on_shard_started = None
        # recovery hook (peer recovery): prepare_shard(shard_routing,
        # engine) → None; may pull files/ops from the primary before the
        # shard is reported started. Runs on the recovery executor, NOT the
        # state-applier thread (the reference recovers on dedicated
        # RECOVERY threads so a long file copy can't stall state
        # application).
        self.prepare_shard = None
        self._recovering: set[str] = set()
        # dangling-indices import (core/gateway/DanglingIndicesState.java):
        # on-disk index dirs unknown to the applied cluster state are
        # offered to the master (Node wires dangling_import), unless a
        # delete tombstone says the index was removed — then the local
        # copy is destroyed so deleted indices stay dead
        self.dangling_import = None
        self._dangling_offered: set[str] = set()
        self._meta_written: dict[str, tuple] = {}
        # completed per-shard recovery records (ref: the indices recovery
        # API, core/action/admin/indices/recovery/ + RestRecoveryAction)
        self.recovery_records: list[dict] = []
        self._recovery_executor = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"recovery[{node_id[:8]}]")
        cluster_service.add_listener(self._cluster_changed)
        # reconcile initial (recovered) state
        self._cluster_changed(ClusterState(), cluster_service.state())

    # ---- reconciler (IndicesClusterStateService.clusterChanged analog) ----

    def _cluster_changed(self, old: ClusterState, new: ClusterState) -> None:
        # shards the routing table places on this node
        local_by_index: dict[str, list[ShardRouting]] = {}
        for s in new.routing_table.on_node(self.node_id):
            local_by_index.setdefault(s.index, []).append(s)

        for name, meta in new.indices.items():
            local = local_by_index.get(name, [])
            if meta.state != "open":
                svc = self.indices.pop(name, None)
                if svc is not None:
                    svc.close()
                continue
            if name not in self.indices:
                if not local:
                    continue                     # nothing of it lives here
                self.indices[name] = IndexService(
                    meta, self.data_path / "indices" / name,
                    local_shards=[s.shard for s in local],
                    breaker_service=self.breaker_service,
                    merge_submit=self.merge_submit,
                    on_engine_failure=self._engine_failed,
                    disk_fault_lookup=lambda: self.disk_fault,
                    reader_swap_lookup=lambda: self.reader_swap_hook,
                    request_cache_lookup=lambda: self.request_cache)
            svc = self.indices[name]
            if meta.mappings != svc.meta.mappings:
                for t, m in (meta.mappings or {}).items():
                    svc.mapper_service.merge(t, m)
            if meta.settings != svc.meta.settings:
                svc.apply_settings(meta)
            svc.meta = meta
            self._write_index_meta(name, meta)
            # create newly assigned shards / drop moved-away ones
            want = {s.shard for s in local}
            for sid in want - set(svc.engines):
                svc.add_local_shard(sid)
            for sid in set(svc.engines) - want:
                svc.remove_local_shard(sid)
            # recover INITIALIZING shards then report started
            # (ShardStateAction). Only act when the callback is wired —
            # during the constructor reconcile it is not yet, and the
            # Node's follow-up recheck must pick these shards up.
            for s in local:
                if s.state != ShardRoutingState.INITIALIZING or \
                        s.allocation_id in self._recovering or \
                        self.on_shard_started is None:
                    continue
                if s.allocation_id in self._reported_started:
                    # the master STILL sees this copy INITIALIZING after
                    # we reported — the report was lost (partition mid-
                    # RPC). Re-send the recorded outcome; without this a
                    # lost report wedges the shard INITIALIZING forever
                    outcome, reason = self._report_outcome.get(
                        s.allocation_id, ("started", None))
                    try:
                        if outcome == "failed":
                            # never promote a failed copy just because
                            # the failure callback is unwired
                            if self.on_shard_failed is not None:
                                self.on_shard_failed(
                                    s, reason or "recovery failed")
                        else:
                            self.on_shard_started(s)
                    except Exception:    # noqa: BLE001 — retry next state
                        pass
                    continue
                self._recovering.add(s.allocation_id)
                try:
                    self._recovery_executor.submit(
                        self._do_recovery, s, svc.engines[s.shard])
                except RuntimeError:             # node closing
                    self._recovering.discard(s.allocation_id)

        for name in list(self.indices):
            if name not in new.indices:
                self.indices[name].close()
                shutil.rmtree(self.data_path / "indices" / name,
                              ignore_errors=True)
                del self.indices[name]
                self._meta_written.pop(name, None)
                self._dangling_offered.discard(name)
        gone = [r["index"] for r in self.recovery_records
                if r["index"] not in new.indices]
        if gone:
            # RecoveryState dies with its shard — purge records of
            # deleted indices so a recreated index starts clean
            self.recovery_records = [r for r in self.recovery_records
                                     if r["index"] in new.indices]
        self._scan_dangling(new)

    # ---- dangling indices (DanglingIndicesState analog) --------------------

    def _write_index_meta(self, name: str, meta) -> None:
        """Stamp the index's metadata into its data directory so a copy
        orphaned by cluster-metadata loss can be re-imported (the
        reference persists IndexMetaData in the index folder)."""
        key = (meta.uuid, meta.version)
        if self._meta_written.get(name) == key:
            return
        d = self.data_path / "indices" / name
        try:
            d.mkdir(parents=True, exist_ok=True)
            tmp = d / "_meta.json.tmp"
            tmp.write_text(json.dumps(meta.to_state_dict()))
            tmp.replace(d / "_meta.json")
            self._meta_written[name] = key
        except OSError:
            pass                                 # retried on a later state

    def _scan_dangling(self, new: ClusterState) -> None:
        """Compare on-disk index dirs against the applied state: offer
        unknown ones to the master for metadata re-import + allocation;
        destroy tombstoned ones (a delete that happened while this node
        was offline must win — removed indices stay dead)."""
        if new.master_node_id is None:
            return                               # no one to offer to
        root = self.data_path / "indices"
        if not root.is_dir():
            return
        tomb_names: set[str] = set()
        tomb_uuids: set[str] = set()
        for t in new.customs.get("index_tombstones", []):
            tomb_names.add(t.get("index"))
            if t.get("uuid"):
                tomb_uuids.add(t["uuid"])
        for d in sorted(root.iterdir()):
            name = d.name
            if not d.is_dir() or name in new.indices \
                    or name in self.indices:
                continue
            raw = None
            try:
                raw = json.loads((d / "_meta.json").read_text())
            except (OSError, json.JSONDecodeError):
                raw = None
            disk_uuid = (raw or {}).get("uuid", "")
            if name in tomb_names or (disk_uuid and
                                      disk_uuid in tomb_uuids):
                shutil.rmtree(d, ignore_errors=True)
                self._dangling_offered.discard(name)
                self._meta_written.pop(name, None)
                continue
            if raw is None or self.dangling_import is None \
                    or name in self._dangling_offered:
                continue
            self._dangling_offered.add(name)
            # the offer RPC can block on master forwarding — never on
            # the state-applier thread
            t = threading.Thread(target=self._offer_dangling,
                                 args=(name, raw),
                                 name=f"dangling[{name}]", daemon=True)
            t.start()

    def _offer_dangling(self, name: str, meta_dict: dict) -> None:
        try:
            self.dangling_import(name, meta_dict)
        except Exception:                        # noqa: BLE001 — retry later
            self._dangling_offered.discard(name)

    on_shard_failed = None

    def _engine_failed(self, index: str, sid: int, reason: str) -> None:
        """An engine self-failed (translog/store IO error): drop the dead
        engine locally and report the copy failed so the master
        reallocates it (IndexShard.failShard → ShardStateAction). Runs on
        the engine's failure thread, never the failing op's."""
        routing = next(
            (s for s in self.cluster_service.state().routing_table
             .on_node(self.node_id)
             if s.index == index and s.shard == sid), None)
        svc = self.indices.get(index)
        if svc is not None:
            try:
                svc.remove_local_shard(sid)
            except Exception:                    # noqa: BLE001 — dying disk
                pass
        if routing is not None:
            # a re-allocation of this copy gets a fresh allocation id; the
            # old report bookkeeping must not leak onto it
            self._reported_started.discard(routing.allocation_id)
            self._report_outcome.pop(routing.allocation_id, None)
            self._recovering.discard(routing.allocation_id)
            if self.on_shard_failed is not None:
                self.on_shard_failed(routing, f"engine failure: {reason}")

    def _do_recovery(self, s: ShardRouting, engine) -> None:
        """Recovery-executor body: run the peer-recovery hook, then report
        started (or failed) to the master via the Node's callbacks."""
        from elasticsearch_tpu.indices.recovery import DelayRecoveryError
        t0 = time.monotonic()           # duration measurement, not epoch
        try:
            if self.prepare_shard is not None:
                self.prepare_shard(s, engine)
        except DelayRecoveryError:
            # source not ready — back off and re-run the reconciler
            # (RecoveryTarget retry/backoff, RecoveryTarget.java:511)
            self._recovering.discard(s.allocation_id)
            t = threading.Timer(0.3, self._retry_reconcile)
            t.daemon = True
            t.start()
            return
        except Exception as e:                   # noqa: BLE001 — report fail
            self._recovering.discard(s.allocation_id)
            # outcome FIRST: a concurrent reconcile that sees the id in
            # _reported_started must never default to "started" for a
            # copy whose recovery failed
            self._report_outcome[s.allocation_id] = \
                ("failed", f"recovery failed: {e}")
            self._reported_started.add(s.allocation_id)
            if self.on_shard_failed is not None:
                self.on_shard_failed(s, f"recovery failed: {e}")
            return
        self._report_outcome[s.allocation_id] = ("started", None)
        self._reported_started.add(s.allocation_id)
        self._recovering.discard(s.allocation_id)
        self._record_recovery(s, engine, t0)
        self.on_shard_started(s)

    def _record_recovery(self, s: ShardRouting, engine, t0: float) -> None:
        """Append a completed-recovery record (the `_recovery` / cat.recovery
        data source; ref: RecoveryState in core/indices/recovery/)."""
        state = self.cluster_service.state()
        source = self.node_id
        if not s.primary:
            primary = next((p for p in
                            state.routing_table.index_shards(s.index)
                            if p.shard == s.shard and p.primary
                            and p.node_id), None)
            if primary is not None:
                source = primary.node_id
        def node_name(nid):
            n = state.nodes.get(nid)
            return n.name if n is not None else nid[:8]
        files = nbytes = 0
        try:
            for p in engine.path.rglob("*"):
                # the recovered file set = the committed store (commit +
                # segment files); the translog is replayed, not copied
                if p.is_file() and "translog" not in p.parts:
                    files += 1
                    nbytes += p.stat().st_size
        except OSError:
            pass
        rtype = "store" if s.primary else "replica"
        repository = snapshot = "n/a"
        meta = state.indices.get(s.index)
        if s.primary and meta is not None and \
                meta.settings.get("index.restore.repository"):
            rtype = "snapshot"
            repository = meta.settings["index.restore.repository"]
            snapshot = meta.settings.get("index.restore.snapshot", "n/a")
        self.recovery_records.append({
            "index": s.index, "shard": s.shard,
            "time_ms": max(int((time.monotonic() - t0) * 1000), 1),
            "type": rtype,
            "stage": "done",
            "source_host": node_name(source),
            "target_host": node_name(self.node_id),
            "repository": repository, "snapshot": snapshot,
            "files": files, "bytes": nbytes, "translog": 0,
        })

    def _retry_reconcile(self) -> None:
        try:
            self.cluster_service.run_task(
                "recovery-retry",
                lambda: self._cluster_changed(self.cluster_service.state(),
                                              self.cluster_service.state()))
        except RuntimeError:
            pass                                 # shutting down

    def unreport(self, allocation_id: str) -> None:
        """Forget a started-report that failed to reach the master so the
        next reconcile re-sends it (the reference resends shardStarted for
        shards still INITIALIZING in a new state)."""
        self._reported_started.discard(allocation_id)
        self._report_outcome.pop(allocation_id, None)

    # ---- metadata CRUD (MetaDataCreateIndexService analog) ----------------

    def _master_op(self, action: str, request: dict, local):
        if self.master_executor is not None:
            return self.master_executor(action, request, local)
        return local()

    def create_index(self, name: str,
                     body: dict | None = None) -> IndexService | None:
        body = body or {}
        return self._master_op("create-index", {"name": name, "body": body},
                               lambda: self._create_index_local(name, body))

    def _create_index_local(self, name: str,
                            body: dict) -> IndexService | None:
        if not name or name.startswith(("_", "-")) or name != name.lower() \
                or any(c in name for c in ' "\\/,|<>?*'):
            raise IllegalArgumentError(f"invalid index name [{name}]")

        def update(state: ClusterState) -> ClusterState:
            if name in state.indices:
                raise IndexAlreadyExistsError(name)
            settings = _normalize_index_settings(body.get("settings", {}))
            # a typo'd store type must fail HERE, not on every later
            # flush (incl. the swallowed background-merge flush) —
            # IndexStoreModule resolves at creation in the reference too
            if "index.store.type" in settings:
                from elasticsearch_tpu.index.segment import (
                    validate_store_type)
                validate_store_type(settings["index.store.type"])
            mappings = dict(body.get("mappings", {}))
            if mappings and "properties" in mappings:
                mappings = {"_doc": mappings}   # typeless API compat
            # apply matching templates; highest order wins conflicts, so
            # with setdefault-application it must be applied FIRST
            # (MetaDataCreateIndexService.java sorts by order descending)
            for tname, tmpl in sorted(state.templates.items(),
                                      key=lambda kv: -kv[1].get("order", 0)):
                import fnmatch as _fn
                patterns = tmpl.get("index_patterns") or \
                    [tmpl.get("template", "")]
                if any(_fn.fnmatch(name, p) for p in patterns if p):
                    for k, v in Settings(
                            tmpl.get("settings", {})).as_dict().items():
                        settings.setdefault(k, v)
                    tmap = tmpl.get("mappings", {})
                    if tmap and "properties" in tmap:
                        tmap = {"_doc": tmap}
                    for t, m in tmap.items():
                        base = mappings.setdefault(t, {"properties": {}})
                        for fname, fdef in m.get("properties", {}).items():
                            base.setdefault("properties", {}).setdefault(
                                fname, fdef)
            sett = Settings(settings)
            # impact-lane knobs validate at creation for the same
            # reason as store.type: a bad value must fail the create
            # request with a 400, not blow up the cluster-state
            # applier (IndexService init) after the create was acked
            from elasticsearch_tpu.search import jit_exec as _jit_exec
            _jit_exec.validate_impact_settings(sett)
            _jit_exec.validate_knn_settings(sett)
            from elasticsearch_tpu.mapping.mapper import (
                validate_vector_mappings)
            validate_vector_mappings(mappings)
            meta = IndexMetadata(
                name=name,
                # ES 2.x default shard count (IndexMetaData
                # SETTING_NUMBER_OF_SHARDS default 5) — parent/routing
                # semantics depend on docs actually spreading over shards
                number_of_shards=sett.get_as_int("index.number_of_shards",
                                                 5),
                number_of_replicas=sett.get_as_int(
                    "index.number_of_replicas", 0),
                settings=settings, mappings=mappings,
                aliases={a: normalize_alias(v)
                         for a, v in body.get("aliases", {}).items()},
                warmers=dict(body.get("warmers", {})),
                creation_date=int(time.time() * 1000),  # wall-clock ok
                uuid=uuid.uuid4().hex[:22])
            new = state.with_(
                indices={**state.indices, name: meta},
                routing_table=state.routing_table.add_index(meta))
            return self.allocation.reroute(new, f"index created [{name}]")

        self.cluster_service.submit_and_wait(f"create-index [{name}]", update)
        return self.indices.get(name)

    def delete_index(self, name: str) -> None:
        self._master_op("delete-index", {"name": name},
                        lambda: self._delete_index_local(name))

    #: delete tombstones kept in cluster state (IndexGraveyard analog):
    #: a node offline during the delete must find the tombstone on
    #: rejoin and destroy its on-disk copy instead of offering it back
    #: as a dangling index
    TOMBSTONE_CAP = 100

    def _delete_index_local(self, name: str) -> None:
        def update(state: ClusterState) -> ClusterState:
            names = self._resolve(state, name)
            indices = dict(state.indices)
            routing = state.routing_table
            tombs = list(state.customs.get("index_tombstones", []))
            for n in names:
                tombs.append({"index": n, "uuid": indices[n].uuid})
                del indices[n]
                routing = routing.remove_index(n)
            tombs = tombs[-self.TOMBSTONE_CAP:]
            return state.with_(indices=indices, routing_table=routing,
                               customs={**state.customs,
                                        "index_tombstones": tombs})
        self.cluster_service.submit_and_wait(f"delete-index [{name}]", update)

    def put_mapping(self, name: str, type_name: str, mapping: dict) -> None:
        self._master_op(
            "put-mapping",
            {"name": name, "type": type_name, "mapping": mapping},
            lambda: self._put_mapping_local(name, type_name, mapping))

    def _put_mapping_local(self, name: str, type_name: str,
                           mapping: dict) -> None:
        def update(state: ClusterState) -> ClusterState:
            if name not in state.indices:
                raise IndexNotFoundError(name)
            meta = state.indices[name]
            if name in self.indices:
                # validate merge against the live mapper first (reference:
                # dry-run merge before committing the mapping update)
                self.indices[name].mapper_service.merge(type_name, mapping)
                merged = self.indices[name].mapper_service.mapping_dict()[
                    type_name]
            else:
                scratch = MapperService(AnalysisRegistry(
                    Settings(meta.settings)))
                for t, m in (meta.mappings or {}).items():
                    scratch.merge(t, m)
                scratch.merge(type_name, mapping)
                merged = scratch.mapping_dict()[type_name]
            new_meta = IndexMetadata(
                **{**meta.__dict__, "version": meta.version + 1,
                   "mappings": {**meta.mappings, type_name: merged}})
            return state.with_(indices={**state.indices, name: new_meta})
        self.cluster_service.submit_and_wait(f"put-mapping [{name}]", update)

    def update_settings(self, name: str, settings: dict) -> None:
        """Per-index dynamic settings (IndexSettingsService analog);
        number_of_replicas changes resize the routing table."""
        self._master_op(
            "update-settings", {"name": name, "settings": settings},
            lambda: self._update_settings_local(name, settings))

    def _update_settings_local(self, name: str, settings: dict) -> None:
        def update(state: ClusterState) -> ClusterState:
            new_indices = dict(state.indices)
            routing = state.routing_table
            for n in self._resolve(state, name):
                meta = state.indices[n]
                merged = {**meta.settings,
                          **_normalize_index_settings(settings)}
                replicas = Settings(merged).get_as_int(
                    "index.number_of_replicas", meta.number_of_replicas)
                new_meta = IndexMetadata(
                    **{**meta.__dict__, "settings": merged,
                       "version": meta.version + 1,
                       "number_of_replicas": replicas})
                new_indices[n] = new_meta
                if replicas != meta.number_of_replicas:
                    routing = routing.update_replica_count(n, replicas)
            new = state.with_(indices=new_indices, routing_table=routing)
            return self.allocation.reroute(new, "settings updated")
        self.cluster_service.submit_and_wait(f"update-settings [{name}]",
                                             update)

    def put_percolator(self, index: str, qid: str, body: dict) -> None:
        """Register a percolator query (stored in IndexMetadata — see
        search/percolator.py for why it is not a hidden doc type here)."""
        from elasticsearch_tpu.search.query_dsl import parse_query
        parse_query(body.get("query"))           # validate at register time
        self._master_op(
            "put-percolator", {"index": index, "id": qid, "body": body},
            lambda: self._put_percolator_local(index, qid, body))

    def _put_percolator_local(self, index: str, qid: str,
                              body: dict) -> None:
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            new_meta = replace(meta, percolators={**meta.percolators,
                                                  qid: body},
                               version=meta.version + 1)
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_and_wait(
            f"put-percolator [{index}/{qid}]", update)

    def delete_percolator(self, index: str, qid: str) -> None:
        self._master_op(
            "delete-percolator", {"index": index, "id": qid},
            lambda: self._delete_percolator_local(index, qid))

    def _delete_percolator_local(self, index: str, qid: str) -> None:
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            pq = {k: v for k, v in meta.percolators.items() if k != qid}
            new_meta = replace(meta, percolators=pq,
                               version=meta.version + 1)
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_and_wait(
            f"delete-percolator [{index}/{qid}]", update)

    def put_warmer(self, index: str, name: str, warmer: dict) -> None:
        """Register a search warmer (ref: IndexWarmersMetaData +
        TransportPutWarmerAction — the warmer source runs against every
        fresh searcher; here registration is the metadata contract, and
        warming happens when a refresh swaps in a new device reader)."""
        self._master_op(
            "put-warmer", {"index": index, "name": name, "body": warmer},
            lambda: self._put_warmer_local(index, name, warmer))

    def _put_warmer_local(self, index: str, name: str, warmer: dict) -> None:
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            new_meta = replace(meta, warmers={**meta.warmers, name: warmer},
                               version=meta.version + 1)
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_and_wait(
            f"put-warmer [{index}/{name}]", update)

    def delete_warmers(self, index: str, names: set[str]) -> None:
        self._master_op(
            "delete-warmer", {"index": index, "names": sorted(names)},
            lambda: self._delete_warmers_local(index, names))

    def _delete_warmers_local(self, index: str, names) -> None:
        names = set(names)
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            keep = {k: v for k, v in meta.warmers.items() if k not in names}
            new_meta = replace(meta, warmers=keep, version=meta.version + 1)
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_and_wait(
            f"delete-warmer [{index}]", update)

    def put_alias(self, index: str, alias: str, body: dict | None = None):
        self._master_op(
            "put-alias", {"index": index, "alias": alias, "body": body},
            lambda: self._put_alias_local(index, alias, body))

    def _put_alias_local(self, index: str, alias: str,
                         body: dict | None = None):
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            new_meta = IndexMetadata(
                **{**meta.__dict__,
                   "aliases": {**meta.aliases, alias: body or {}}})
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_and_wait(f"put-alias [{alias}]", update)

    def delete_alias(self, index: str, alias: str):
        self._master_op(
            "delete-alias", {"index": index, "alias": alias},
            lambda: self._delete_alias_local(index, alias))

    def _delete_alias_local(self, index: str, alias: str):
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            aliases = {k: v for k, v in meta.aliases.items() if k != alias}
            new_meta = IndexMetadata(**{**meta.__dict__, "aliases": aliases})
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_and_wait(f"delete-alias [{alias}]",
                                             update)

    def set_index_state(self, index: str, new_state: str):
        """open/close an index (ref: MetaDataIndexStateService — state
        flips in IndexMetaData; closed indices keep their files but serve
        no reads/writes)."""
        self._master_op(
            "index-state", {"index": index, "state": new_state},
            lambda: self._set_index_state_local(index, new_state))

    def _set_index_state_local(self, index: str, new_state: str):
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            if meta.state == new_state:
                return state
            new_meta = IndexMetadata(**{**meta.__dict__,
                                        "state": new_state})
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_and_wait(
            f"{new_state}-index [{index}]", update)

    # ---- resolution -------------------------------------------------------

    def _resolve(self, state: ClusterState, expr: str) -> list[str]:
        """Index expression → concrete names (aliases + wildcards;
        reference: IndexNameExpressionResolver)."""
        import fnmatch as _fn
        names: list[str] = []
        for part in expr.split(","):
            part = part.strip()
            if part in ("_all", "*", ""):
                names.extend(state.indices)
                continue
            if "*" in part:
                matched = [n for n in state.indices if _fn.fnmatch(n, part)]
                names.extend(matched)
                continue
            if part in state.indices:
                names.append(part)
                continue
            via_alias = [n for n, m in state.indices.items()
                         if part in m.aliases]
            if via_alias:
                names.extend(via_alias)
                continue
            raise IndexNotFoundError(part)
        seen = set()
        out = []
        for n in names:
            if n not in seen:
                seen.add(n)
                out.append(n)
        return out

    def resolve_open(self, expr: str) -> list[str]:
        """Search/read resolution: wildcard expansion skips closed
        indices, explicitly naming one raises IndexClosedError (403) —
        ref: IndexNameExpressionResolver + IndexClosedException."""
        from elasticsearch_tpu.common.errors import IndexClosedError
        state = self.cluster_service.state()
        # expand each explicit (non-wildcard) part to the concrete index
        # names it denotes — an alias to a closed index is as explicit as
        # naming the index itself
        explicit: set[str] = set()
        for p in (expr or "_all").split(","):
            p = p.strip()
            if not p or "*" in p or p == "_all":
                continue
            try:
                explicit.update(self._resolve(state, p))
            except IndexNotFoundError:
                pass
        out = []
        for n in self._resolve(state, expr or "_all"):
            if state.indices[n].state == "close":
                if n in explicit:
                    raise IndexClosedError(f"closed index [{n}]")
                continue
            out.append(n)
        return out

    def resolve(self, expr: str) -> list[str]:
        return self._resolve(self.cluster_service.state(), expr)

    def index(self, name: str) -> IndexService:
        names = self.resolve(name)
        if not names:
            raise IndexNotFoundError(name)
        svc = self.indices.get(names[0])
        if svc is None:
            raise IndexNotFoundError(names[0])
        return svc

    def has_index(self, name: str) -> bool:
        try:
            return bool(self.resolve(name))
        except IndexNotFoundError:
            return False

    def close(self):
        self._recovery_executor.shutdown(wait=False, cancel_futures=True)
        for svc in self.indices.values():
            svc.close()
