"""IndicesService / IndexService — node-level index containers.

Reference: core/indices/IndicesService.java creates a per-index injector and
per-shard IndexShard instances; IndicesClusterStateService
(core/indices/cluster/IndicesClusterStateService.java:71) reconciles the
published cluster state against local shards. Here the reconciler listens on
ClusterService and creates/removes IndexService objects, each owning one
Engine per local shard.
"""

from __future__ import annotations

import shutil
import time
import uuid
from pathlib import Path

from elasticsearch_tpu.analysis import AnalysisRegistry
from elasticsearch_tpu.cluster.routing import OperationRouting
from elasticsearch_tpu.cluster.state import ClusterState, IndexMetadata
from elasticsearch_tpu.common.errors import (
    IndexAlreadyExistsError, IndexNotFoundError, IllegalArgumentError)
from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.mapping import MapperService


class IndexService:
    """Per-index container: mapper service + one engine per local shard."""

    def __init__(self, meta: IndexMetadata, path: Path):
        self.name = meta.name
        self.meta = meta
        self.path = path
        index_settings = Settings(meta.settings)
        self.analysis = AnalysisRegistry(index_settings)
        self.mapper_service = MapperService(self.analysis)
        for type_name, mapping in (meta.mappings or {}).items():
            self.mapper_service.merge(type_name, mapping)
        self.shard_engines: list[Engine] = []
        for sid in range(meta.number_of_shards):
            self.shard_engines.append(
                Engine(path / str(sid), self.mapper_service, index_settings))

    def shard_for(self, doc_id: str, routing: str | None = None) -> Engine:
        sid = OperationRouting.shard_id(doc_id, self.meta.number_of_shards,
                                        routing)
        return self.shard_engines[sid]

    def refresh(self):
        for e in self.shard_engines:
            e.refresh()

    def flush(self):
        for e in self.shard_engines:
            e.flush()

    def force_merge(self, max_num_segments: int = 1):
        for e in self.shard_engines:
            e.force_merge(max_num_segments)

    def num_docs(self) -> int:
        return sum(e.num_docs for e in self.shard_engines)

    def stats(self) -> dict:
        agg = {"index_total": 0, "delete_total": 0, "refresh_total": 0,
               "flush_total": 0, "merge_total": 0, "index_time_ms": 0.0}
        segs = []
        for e in self.shard_engines:
            s = e.stats
            agg["index_total"] += s.index_total
            agg["delete_total"] += s.delete_total
            agg["refresh_total"] += s.refresh_total
            agg["flush_total"] += s.flush_total
            agg["merge_total"] += s.merge_total
            agg["index_time_ms"] += s.index_time_ms
            segs.extend(e.segment_stats())
        return {
            "docs": {"count": self.num_docs()},
            "indexing": {"index_total": agg["index_total"],
                         "delete_total": agg["delete_total"],
                         "index_time_in_millis": int(agg["index_time_ms"])},
            "refresh": {"total": agg["refresh_total"]},
            "flush": {"total": agg["flush_total"]},
            "merges": {"total": agg["merge_total"]},
            "segments": {"count": len(segs),
                         "memory_in_bytes": sum(s["memory_bytes"] for s in segs)},
        }

    def close(self):
        for e in self.shard_engines:
            e.close()


class IndicesService:
    def __init__(self, data_path: Path, cluster_service, node_id: str):
        self.data_path = Path(data_path)
        self.cluster_service = cluster_service
        self.node_id = node_id
        self.indices: dict[str, IndexService] = {}
        cluster_service.add_listener(self._cluster_changed)
        # reconcile initial (recovered) state
        self._cluster_changed(ClusterState(), cluster_service.state())

    # ---- reconciler (IndicesClusterStateService.clusterChanged analog) ----

    def _cluster_changed(self, old: ClusterState, new: ClusterState) -> None:
        for name, meta in new.indices.items():
            if name not in self.indices and meta.state == "open":
                self.indices[name] = IndexService(
                    meta, self.data_path / "indices" / name)
            elif name in self.indices:
                svc = self.indices[name]
                if meta.state == "close":
                    svc.close()
                    del self.indices[name]
                elif meta.mappings != svc.meta.mappings:
                    for t, m in (meta.mappings or {}).items():
                        svc.mapper_service.merge(t, m)
                    svc.meta = meta
                else:
                    svc.meta = meta
        for name in list(self.indices):
            if name not in new.indices:
                self.indices[name].close()
                shutil.rmtree(self.data_path / "indices" / name,
                              ignore_errors=True)
                del self.indices[name]

    # ---- metadata CRUD (MetaDataCreateIndexService analog) ----------------

    def create_index(self, name: str, body: dict | None = None) -> IndexService:
        body = body or {}
        if not name or name.startswith(("_", "-")) or name != name.lower() \
                or any(c in name for c in ' "\\/,|<>?*'):
            raise IllegalArgumentError(f"invalid index name [{name}]")

        def update(state: ClusterState) -> ClusterState:
            if name in state.indices:
                raise IndexAlreadyExistsError(name)
            settings = dict(Settings(body.get("settings", {})))
            mappings = dict(body.get("mappings", {}))
            if mappings and "properties" in mappings:
                mappings = {"_doc": mappings}   # typeless API compat
            # apply matching templates (MetaDataCreateIndexService template merge)
            for tname, tmpl in sorted(state.templates.items(),
                                      key=lambda kv: kv[1].get("order", 0)):
                import fnmatch as _fn
                patterns = tmpl.get("index_patterns") or [tmpl.get("template", "")]
                if any(_fn.fnmatch(name, p) for p in patterns if p):
                    for k, v in Settings(tmpl.get("settings", {})).as_dict().items():
                        settings.setdefault(k, v)
                    tmap = tmpl.get("mappings", {})
                    if tmap and "properties" in tmap:
                        tmap = {"_doc": tmap}
                    for t, m in tmap.items():
                        base = mappings.setdefault(t, {"properties": {}})
                        for fname, fdef in m.get("properties", {}).items():
                            base.setdefault("properties", {}).setdefault(fname, fdef)
            sett = Settings(settings)
            meta = IndexMetadata(
                name=name,
                number_of_shards=sett.get_as_int("index.number_of_shards", 1),
                number_of_replicas=sett.get_as_int("index.number_of_replicas", 0),
                settings=settings, mappings=mappings,
                aliases={a: (v or {}) for a, v in body.get("aliases", {}).items()},
                creation_date=int(time.time() * 1000),
                uuid=uuid.uuid4().hex[:22])
            return state.with_(
                indices={**state.indices, name: meta},
                routing_table=state.routing_table.add_index(meta, self.node_id))

        self.cluster_service.submit_state_update(f"create-index [{name}]", update)
        return self.indices[name]

    def delete_index(self, name: str) -> None:
        def update(state: ClusterState) -> ClusterState:
            names = self._resolve(state, name)
            indices = dict(state.indices)
            routing = state.routing_table
            for n in names:
                del indices[n]
                routing = routing.remove_index(n)
            return state.with_(indices=indices, routing_table=routing)
        self.cluster_service.submit_state_update(f"delete-index [{name}]", update)

    def put_mapping(self, name: str, type_name: str, mapping: dict) -> None:
        def update(state: ClusterState) -> ClusterState:
            if name not in state.indices:
                raise IndexNotFoundError(name)
            meta = state.indices[name]
            # validate merge against a scratch mapper first (reference:
            # dry-run merge before committing the mapping update)
            self.indices[name].mapper_service.merge(type_name, mapping)
            merged = self.indices[name].mapper_service.mapping_dict()[type_name]
            new_meta = IndexMetadata(
                **{**meta.__dict__,
                   "mappings": {**meta.mappings, type_name: merged}})
            return state.with_(indices={**state.indices, name: new_meta})
        self.cluster_service.submit_state_update(f"put-mapping [{name}]", update)

    def put_alias(self, index: str, alias: str, body: dict | None = None):
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            new_meta = IndexMetadata(
                **{**meta.__dict__,
                   "aliases": {**meta.aliases, alias: body or {}}})
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_state_update(f"put-alias [{alias}]", update)

    def delete_alias(self, index: str, alias: str):
        def update(state: ClusterState) -> ClusterState:
            if index not in state.indices:
                raise IndexNotFoundError(index)
            meta = state.indices[index]
            aliases = {k: v for k, v in meta.aliases.items() if k != alias}
            new_meta = IndexMetadata(**{**meta.__dict__, "aliases": aliases})
            return state.with_(indices={**state.indices, index: new_meta})
        self.cluster_service.submit_state_update(f"delete-alias [{alias}]", update)

    # ---- resolution -------------------------------------------------------

    def _resolve(self, state: ClusterState, expr: str) -> list[str]:
        """Index expression → concrete names (aliases + wildcards;
        reference: IndexNameExpressionResolver)."""
        import fnmatch as _fn
        names: list[str] = []
        for part in expr.split(","):
            part = part.strip()
            if part in ("_all", "*", ""):
                names.extend(state.indices)
                continue
            if "*" in part:
                matched = [n for n in state.indices if _fn.fnmatch(n, part)]
                names.extend(matched)
                continue
            if part in state.indices:
                names.append(part)
                continue
            via_alias = [n for n, m in state.indices.items()
                         if part in m.aliases]
            if via_alias:
                names.extend(via_alias)
                continue
            raise IndexNotFoundError(part)
        seen = set()
        out = []
        for n in names:
            if n not in seen:
                seen.add(n)
                out.append(n)
        return out

    def resolve(self, expr: str) -> list[str]:
        return self._resolve(self.cluster_service.state(), expr)

    def index(self, name: str) -> IndexService:
        names = self.resolve(name)
        if not names:
            raise IndexNotFoundError(name)
        return self.indices[names[0]]

    def has_index(self, name: str) -> bool:
        try:
            return bool(self.resolve(name))
        except IndexNotFoundError:
            return False

    def close(self):
        for svc in self.indices.values():
            svc.close()
