from elasticsearch_tpu.indices.service import IndicesService, IndexService

__all__ = ["IndicesService", "IndexService"]
