"""Peer recovery — bring an initializing shard copy in sync with its
active primary.

Reference: core/indices/recovery/ — the target sends StartRecoveryRequest
(RecoveryTarget.doRecovery, RecoveryTarget.java:157); the source answers by
driving the copy (RecoverySourceHandler.recoverToTarget, :125-152):

* **phase1** (:166) — diff the file sets by checksum (Store.MetadataSnapshot,
  core/index/store/Store.java:87) and stream only missing/changed files in
  chunks (RecoveryFileChunkRequest); identical file sets skip the copy
  entirely (the effect the reference gets from synced-flush sync_ids,
  SyncedFlushService.java:60);
* **phase2** (:146) — replay every translog op captured during the copy
  through a pinned view (Translog.java:506); replica-side apply is
  version-deduped so overlap with live replicated writes is harmless;
* finalize (:152) — the target reports shard-started to the master.

Direction matches the reference: the target asks, the source pushes
file_chunk / clean_files / translog_ops RPCs back to the target.
"""

from __future__ import annotations

import os
import time
from pathlib import Path

from elasticsearch_tpu.index.translog import TranslogOp
from elasticsearch_tpu.transport.service import RemoteTransportError

START_RECOVERY = "internal:index/shard/recovery/start_recovery"
FILE_CHUNK = "internal:index/shard/recovery/file_chunk"
CLEAN_FILES = "internal:index/shard/recovery/clean_files"
TRANSLOG_OPS = "internal:index/shard/recovery/translog_ops"

CHUNK_SIZE = 512 * 1024


class RecoveryFailedError(Exception):
    pass


class DelayRecoveryError(Exception):
    """The source isn't ready (e.g. primary not active here yet) — the
    target should retry, not fail the shard (RecoveryTarget retry/backoff,
    RecoveryTarget.java:511)."""


class PeerRecoveryService:
    """Both halves of peer recovery, registered on every node."""

    def __init__(self, node):
        self.node = node
        ts = node.transport_service
        # the source handler blocks while streaming files; keep it off the
        # pools used by writes (dedicated recovery channels in the
        # reference, NettyTransport.java:871)
        ts.register_request_handler(START_RECOVERY, self._handle_start,
                                    executor="recovery", sync=True)
        ts.register_request_handler(FILE_CHUNK, self._handle_file_chunk,
                                    executor="recovery", sync=True)
        ts.register_request_handler(CLEAN_FILES, self._handle_clean_files,
                                    executor="recovery", sync=True)
        ts.register_request_handler(TRANSLOG_OPS, self._handle_translog_ops,
                                    executor="recovery", sync=True)
        self.stats = {"recoveries": 0, "files_sent": 0, "files_skipped": 0,
                      "bytes_sent": 0, "ops_replayed": 0}
        # (index, shard) → source node_id of the recovery THIS target is
        # currently running: inbound chunk/cleanup/ops RPCs from any
        # other node are stale (a source we abandoned after it left the
        # state) and must not interleave with the live stream
        self._active_sources: dict[tuple[str, int], str] = {}
        # (index, shard) → monotonic time of the last inbound recovery
        # RPC: the liveness signal that lets the target distinguish "a
        # big phase1 is streaming" from "the start request (or the whole
        # stream) was swallowed by a partition" — the latter must retry
        # in seconds, not wait out the full recovery deadline
        self._last_activity: dict[tuple[str, int], float] = {}

    # ---- target side -------------------------------------------------------

    def recover_shard(self, shard_routing, engine) -> None:
        """IndicesService.prepare_shard hook: called with an INITIALIZING
        shard before it is reported started. Primaries recover locally
        (Engine.__init__ already replayed the on-disk commit + translog —
        StoreRecovery analog) or from a snapshot repository when the index
        carries a restore marker; replicas pull from the active primary."""
        if shard_routing.primary:
            repo = engine.settings.get("index.restore.repository")
            if repo and engine.num_docs == 0:
                # restore recovery source (RestoreService): pull the
                # snapshot's files instead of starting empty. Non-empty
                # engines are already-restored copies re-initializing
                # after a local restart — leave them alone.
                self.node.snapshots_service.repository(repo).restore_shard(
                    engine,
                    engine.settings.get("index.restore.source_index",
                                        shard_routing.index),
                    shard_routing.shard,
                    engine.settings.get("index.restore.snapshot"))
            return                               # local store recovery
        state = self.node.cluster_service.state()
        pr = state.routing_table.primary(shard_routing.index,
                                         shard_routing.shard)
        if pr is None or not pr.active:
            raise DelayRecoveryError(
                f"[{shard_routing.index}][{shard_routing.shard}] primary "
                "not active yet")
        source_node = state.node(pr.node_id)
        if source_node is None:
            raise DelayRecoveryError("primary node not in cluster state")
        local = self.node.transport_service.local_node
        engine.pin_commit(flush_first=False)     # block local flush/merge
        skey = (shard_routing.index, shard_routing.shard)
        self._active_sources[skey] = source_node.node_id
        self._last_activity[skey] = time.monotonic()
        try:                                     # while files stream in
            # timeout rides the POLL below (which can also cancel on
            # source-left); a transport-level timer would complete the
            # future with ReceiveTimeoutError and skip the retry path
            fut = self.node.transport_service.send_request(
                source_node, START_RECOVERY,
                {"index": shard_routing.index, "shard": shard_routing.shard,
                 "target_node": {"node_id": local.node_id,
                                 "name": local.name,
                                 "host": local.address.host,
                                 "port": local.address.port,
                                 "version": local.version},
                 "manifest": engine.file_manifest()},
                timeout=None)
            # poll instead of a blind 120 s block: a partition can swallow
            # the source mid-recovery, and the reference CANCELS in-flight
            # recoveries when the source node leaves the cluster state
            # (RecoveriesCollection.cancelRecoveriesForShard) rather than
            # waiting out the RPC timeout — retry then targets whatever
            # primary the healed cluster elects
            import concurrent.futures as _cf
            deadline = time.monotonic() + 125.0
            while True:
                try:
                    fut.result(timeout=1.0)
                    break
                except _cf.TimeoutError:
                    if time.monotonic() > deadline:
                        raise DelayRecoveryError(
                            "recovery start timed out") from None
                    # liveness: no inbound recovery RPC for this long
                    # means the start request or the stream itself was
                    # lost (dropped frames) — retry instead of waiting
                    # out the whole deadline with a wedged shard
                    if time.monotonic() - \
                            self._last_activity.get(skey, 0.0) > 15.0:
                        raise DelayRecoveryError(
                            "recovery stalled: no traffic from source "
                            "for 15s") from None
                    now = self.node.cluster_service.state()
                    cur = now.routing_table.primary(
                        shard_routing.index, shard_routing.shard)
                    if cur is None or cur.node_id != source_node.node_id \
                            or source_node.node_id not in now.nodes:
                        raise DelayRecoveryError(
                            "recovery source left the cluster") from None
        except RemoteTransportError as e:
            # a source-side delay crosses the wire as RemoteTransportError;
            # surface it as the retryable kind, not a shard failure
            if e.error_type == "DelayRecoveryError":
                raise DelayRecoveryError(e.reason) from None
            raise
        finally:
            self._active_sources.pop(skey, None)
            self._last_activity.pop(skey, None)
            engine.unpin_commit()

    # ---- source side -------------------------------------------------------

    def _handle_start(self, request: dict, source) -> dict:
        from elasticsearch_tpu.transport.service import (
            DiscoveryNode, TransportAddress)
        index, shard = request["index"], request["shard"]
        state = self.node.cluster_service.state()
        pr = state.routing_table.primary(index, shard)
        if pr is None or pr.node_id != self.node.node_id:
            raise DelayRecoveryError(
                f"[{index}][{shard}] primary does not live on this node")
        svc = self.node.indices_service.indices.get(index)
        engine = svc.engines.get(shard) if svc is not None else None
        if engine is None:
            raise DelayRecoveryError(f"[{index}][{shard}] engine not open")
        from elasticsearch_tpu.transport.stream import (
            MINIMUM_COMPATIBLE_VERSION)
        tn = request["target_node"]
        # carry the target's wire version so streamed chunks/ops
        # serialize at the negotiated generation; a request WITHOUT the
        # key comes from an older-generation node, so the conservative
        # fallback is the minimum compatible version (defaulting to
        # CURRENT would write gated fields the old peer cannot parse)
        target = DiscoveryNode(
            tn["node_id"], tn["name"],
            TransportAddress(tn["host"], tn["port"]),
            version=tn.get("version", MINIMUM_COMPATIBLE_VERSION))
        t0 = time.perf_counter()
        # phase1 prologue: pin the translog FIRST (so no flush anywhere can
        # trim ops we must replay), then flush AND pin the commit so a
        # concurrent merge can't delete segment files mid-stream. The view
        # starts at the pre-flush commit, so phase2 re-sends some ops that
        # ended up inside the new commit — harmless, replica apply is
        # version-idempotent.
        view_gen = engine.translog.acquire_view()
        engine.pin_commit()
        try:
            files_sent, bytes_sent, skipped = self._phase1(
                engine, engine.file_manifest(), target, index, shard,
                request["manifest"])
            ops = engine.translog.ops_since(view_gen)
            self._phase2(engine, target, index, shard, ops)
        finally:
            engine.unpin_commit()
            engine.translog.release_view(view_gen)
        self.stats["recoveries"] += 1
        self.stats["files_sent"] += files_sent
        self.stats["files_skipped"] += skipped
        self.stats["bytes_sent"] += bytes_sent
        self.stats["ops_replayed"] += len(ops)
        return {"files_sent": files_sent, "files_skipped": skipped,
                "bytes_sent": bytes_sent, "ops_replayed": len(ops),
                "took_ms": int((time.perf_counter() - t0) * 1e3)}

    def _phase1(self, engine, source_manifest: dict, target, index: str,
                shard: int, target_manifest: dict) -> tuple[int, int, int]:
        to_send = [rel for rel, sig in source_manifest.items()
                   if target_manifest.get(rel) != sig]
        skipped = len(source_manifest) - len(to_send)
        # commit.json must land last: it is the atomic install point
        to_send.sort(key=lambda rel: rel == "commit.json")
        bytes_sent = 0
        for rel in to_send:
            data = (engine.path / rel).read_bytes()
            total = len(data)
            offsets = range(0, total, CHUNK_SIZE) if total else [0]
            for off in offsets:
                chunk = data[off:off + CHUNK_SIZE]
                # 15 s per chunk: plenty for a 512 KiB in-process hop,
                # and under injected drops the failure surfaces as a
                # clean retryable recovery failure in seconds instead
                # of a minute-long wedge per lost frame
                self.node.transport_service.submit_request(
                    target, FILE_CHUNK,
                    {"index": index, "shard": shard, "path": rel,
                     "offset": off, "data": chunk, "total": total},
                    timeout=15.0)
                bytes_sent += len(chunk)
        # install: drop stale files, open the commit
        self.node.transport_service.submit_request(
            target, CLEAN_FILES,
            {"index": index, "shard": shard,
             "keep": sorted(source_manifest)}, timeout=15.0)
        return len(to_send), bytes_sent, skipped

    def _phase2(self, engine, target, index: str, shard: int,
                ops: list[TranslogOp], batch: int = 500) -> None:
        for i in range(0, len(ops), batch):
            chunk = [{"op": o.op, "id": o.doc_id, "version": o.version,
                      "source": o.source, "routing": o.routing}
                     for o in ops[i:i + batch]]
            self.node.transport_service.submit_request(
                target, TRANSLOG_OPS,
                {"index": index, "shard": shard, "ops": chunk},
                timeout=15.0)

    # ---- target-side handlers (driven by the source) -----------------------

    def _target_engine(self, request: dict):
        svc = self.node.indices_service.indices.get(request["index"])
        engine = svc.engines.get(request["shard"]) if svc is not None else None
        if engine is None:
            raise RecoveryFailedError(
                f"[{request['index']}][{request['shard']}] target engine "
                "not open")
        return engine

    def _check_source(self, request: dict, source) -> None:
        """Inbound recovery traffic must come from the source THIS
        target's current recovery targets — after a cancel-on-source-left
        retry, the abandoned source may still be streaming, and two
        sources interleaving writes into the same files corrupts the
        shard (RecoveriesCollection's per-recovery session discipline)."""
        skey = (request["index"], request["shard"])
        want = self._active_sources.get(skey)
        if want is None or source.node_id != want:
            raise RecoveryFailedError(
                f"[{request['index']}][{request['shard']}] recovery "
                f"traffic from stale source [{source.node_id}]"
                f" (current: [{want}])")
        self._last_activity[skey] = time.monotonic()

    def _handle_file_chunk(self, request: dict, source) -> dict:
        self._check_source(request, source)
        engine = self._target_engine(request)
        rel = request["path"]
        if ".." in rel or rel.startswith("/"):
            raise RecoveryFailedError(f"illegal recovery path [{rel}]")
        dest: Path = engine.path / rel
        dest.parent.mkdir(parents=True, exist_ok=True)
        # first chunk of a file replaces any stale copy
        tmp = dest.with_name(dest.name + ".rec")
        mode = "r+b" if request["offset"] > 0 and tmp.exists() else "wb"
        with open(tmp, mode) as f:
            f.seek(request["offset"])
            f.write(request["data"])
            received = f.tell()
        if received >= request["total"]:
            os.replace(tmp, dest)
        return {}

    def _handle_clean_files(self, request: dict, source) -> dict:
        self._check_source(request, source)
        engine = self._target_engine(request)
        keep = set(request["keep"])
        # remove files of stale segments the source's commit doesn't know
        for seg_dir in engine.path.glob("seg_*"):
            # recursive: nested child blocks live in subdirectories
            for f in sorted(seg_dir.rglob("*"), reverse=True):
                if f.is_file():
                    rel = str(f.relative_to(engine.path))
                    if rel not in keep:
                        f.unlink(missing_ok=True)
                elif f.is_dir() and not any(f.iterdir()):
                    f.rmdir()
            if not any(seg_dir.iterdir()):
                seg_dir.rmdir()
        engine.install_recovered_commit()
        return {}

    def _handle_translog_ops(self, request: dict, source) -> dict:
        from elasticsearch_tpu.index.translog import OP_INDEX
        self._check_source(request, source)
        engine = self._target_engine(request)
        for op in request["ops"]:
            if op["op"] == OP_INDEX:
                engine.index_replica(op["id"], op["source"], op["version"],
                                     routing=op.get("routing"))
            else:
                engine.delete_replica(op["id"], op["version"])
        engine.refresh()
        return {}
