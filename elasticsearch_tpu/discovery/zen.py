"""ZenDiscovery — ping-based membership, master election, join/rejoin.

Reference: core/discovery/zen/ZenDiscovery.java:76 — unicast ping
(ping/UnicastZenPing.java), ElectMasterService ordered election gated on
minimum_master_nodes (elect/ElectMasterService.java), join via
MembershipAction + NodeJoinController (accumulate joins until quorum, then
become master), two-way fault detection (:97-98,177-181), rejoin on master
loss (:78,129), master step-down when it loses its quorum
(handleMinimumMasterNodesChanged / NodesFaultDetection path).

The publish data path is PublishClusterStateAction (publish.py); the
master's ClusterService.publish hook points at ZenDiscovery.publish.
"""

from __future__ import annotations

import threading
import time

from elasticsearch_tpu.cluster.state import (
    ClusterState, NO_MASTER_BLOCK)
from elasticsearch_tpu.cluster.service import URGENT, ClusterService
from elasticsearch_tpu.discovery.fd import (
    MasterFaultDetection, NodesFaultDetection, NotTheMasterError)
from elasticsearch_tpu.discovery.publish import (
    FailedToCommitClusterStateError, PublishClusterStateAction)
from elasticsearch_tpu.transport.service import (
    DiscoveryNode, TransportAddress, TransportService)

PING_ACTION = "internal:discovery/zen/ping"
JOIN_ACTION = "internal:discovery/zen/join"
LEAVE_ACTION = "internal:discovery/zen/leave"


class ZenDiscovery:
    def __init__(self, transport: TransportService,
                 cluster_service: ClusterService, allocation,
                 seed_provider, cluster_name: str = "elasticsearch-tpu",
                 min_master_nodes: int = 1, gateway_fn=None,
                 ping_timeout: float = 1.0, fd_interval: float = 0.5,
                 fd_timeout: float = 1.0, fd_retries: int = 3,
                 publish_timeout: float = 10.0):
        self.transport = transport
        self.cluster_service = cluster_service
        self.allocation = allocation
        self.seed_provider = seed_provider
        self.cluster_name = cluster_name
        self.min_master_nodes = min_master_nodes
        self.gateway_fn = gateway_fn             # state → state (metadata)
        self.ping_timeout = ping_timeout
        self.publisher = PublishClusterStateAction(transport, cluster_service,
                                                   publish_timeout)
        self.publisher.required_acks_fn = lambda: self.min_master_nodes
        self.publisher.expected_master_fn = lambda: self._election_winner
        self.master_fd = MasterFaultDetection(transport, fd_interval,
                                              fd_timeout, fd_retries)
        self.nodes_fd = NodesFaultDetection(transport, fd_interval,
                                            fd_timeout, fd_retries)
        self.master_fd.on_master_failure = self._on_master_failure
        self.master_fd._is_master_fn = self.is_master
        self.nodes_fd.on_node_failure = self._on_node_failure
        self.nodes_fd._current_master_fn = \
            lambda: self.cluster_service.state().master_node_id
        self._running = False
        self._join_thread: threading.Thread | None = None
        self._join_lock = threading.Lock()
        # node_id → (node, vote timestamp); votes expire so dead electors
        # can't satisfy a later quorum (NodeJoinController election context)
        self._pending_joins: dict[str, tuple[DiscoveryNode, float]] = {}
        self._votes_lock = threading.Lock()
        self.JOIN_VOTE_TTL = 10.0
        self._last_master_id: str | None = None
        # who the last ping round said should win; we only accumulate join
        # votes while we believe that is US — otherwise two nodes can
        # each assemble an overlapping "quorum" (we voted for A while
        # counting B's vote for us) and split-brain
        self._election_winner: str | None = None
        transport.register_request_handler(PING_ACTION, self._handle_ping,
                                           executor="same", sync=True)
        transport.register_request_handler(JOIN_ACTION, self._handle_join)
        transport.register_request_handler(LEAVE_ACTION, self._handle_leave,
                                           sync=True)
        cluster_service.add_listener(self._cluster_changed)
        cluster_service.publish = self.publish

    # ---- lifecycle ---------------------------------------------------------

    def start(self, initial_state_timeout: float = 10.0) -> None:
        """Start the join loop and block until a master is known
        (Node.start waitForInitialState, core/node/Node.java:261)."""
        self._running = True
        self._ensure_join_thread()
        deadline = time.monotonic() + initial_state_timeout
        while time.monotonic() < deadline:
            if self.cluster_service.state().master_node_id is not None:
                return
            time.sleep(0.01)
        raise TimeoutError("discovery: no master elected within timeout")

    def stop(self) -> None:
        self._running = False
        self.master_fd.stop()
        self.nodes_fd.stop()
        # best-effort leave notification (ZenDiscovery.doStop sends leave)
        state = self.cluster_service.state()
        master = state.master_node
        local_id = self.transport.local_node.node_id
        if master is not None and master.node_id != local_id:
            try:
                self.transport.submit_request(
                    master, LEAVE_ACTION, {"node_id": local_id}, timeout=1.0)
            except Exception:                    # noqa: BLE001 — going down
                pass

    def is_master(self) -> bool:
        state = self.cluster_service.state()
        return state.master_node_id == self.transport.local_node.node_id

    # ---- publish (master → everyone) --------------------------------------

    def publish(self, new: ClusterState, old: ClusterState) -> None:
        try:
            self.publisher.publish(new, old)
        except FailedToCommitClusterStateError:
            # we could not assemble a master-eligible quorum for this
            # state: we are (at best) a minority master. Step down NOW
            # and rejoin (ZenDiscovery rejoins on failed publish) — the
            # failed update's caller sees the exception, nothing applied.
            # This runs on the cluster-service executor, so mutating
            # directly is the serialized path.
            current = self.cluster_service.state()
            if current.master_node_id == self.transport.local_node.node_id:
                self._election_winner = None     # re-decide from pings
                self.cluster_service.apply_new_state(current.with_(
                    master_node_id=None,
                    blocks=current.blocks | {NO_MASTER_BLOCK},
                    version=current.version))
                self._ensure_join_thread()
            raise

    # ---- ping / election ---------------------------------------------------

    def _ping_all(self) -> list[dict]:
        from elasticsearch_tpu.transport.stream import (
            MINIMUM_COMPATIBLE_VERSION)
        local = self.transport.local_node
        # ping the configured seeds PLUS every node of the last cluster
        # state (UnicastZenPing builds its target set the same way via
        # its ClusterState provider): a node that joined after boot —
        # e.g. a replacement for a dead seed — must still be countable
        # toward the election quorum after the master is lost, even
        # though no static unicast entry names it
        targets = list(self.seed_provider())
        seen = set(targets)
        try:
            for n in self.cluster_service.state().nodes.values():
                if n.address not in seen:
                    seen.add(n.address)
                    targets.append(n.address)
        except Exception:                        # noqa: BLE001 — pre-state
            pass
        responses = []
        for addr in targets:
            if addr == local.address:
                continue
            # first contact: the peer's wire version is unknown, so ping
            # at the minimum compatible generation (UnicastZenPing sends
            # pings at the minimum compatible version for the same
            # reason) — gated fields stay off the wire until the
            # handshake learns the real version
            probe = DiscoveryNode("?", "?", addr,
                                  version=MINIMUM_COMPATIBLE_VERSION)
            try:
                r = self.transport.submit_request(
                    probe, PING_ACTION, {"cluster_name": self.cluster_name},
                    timeout=self.ping_timeout)
            except Exception:                    # noqa: BLE001 — dead seed
                continue
            if r.get("cluster_name") == self.cluster_name:
                responses.append(r)
        return responses

    @staticmethod
    def _node_from_ping(r: dict) -> DiscoveryNode:
        return DiscoveryNode(
            r["node_id"], r["name"], TransportAddress(r["host"], r["port"]),
            attributes=tuple(sorted(r.get("attributes", {}).items())),
            version=r.get("version", 0))

    def _ensure_join_thread(self) -> None:
        with self._join_lock:
            if self._join_thread is not None and self._join_thread.is_alive():
                return
            self._join_thread = threading.Thread(
                target=self._join_loop, daemon=True,
                name=f"zen_join[{self.transport.local_node.name}]")
            self._join_thread.start()

    def _join_loop(self) -> None:
        while self._running and \
                self.cluster_service.state().master_node_id is None:
            try:
                self._find_master_and_join()
            except Exception:                    # noqa: BLE001 — retry
                pass
            time.sleep(0.1)

    def _find_master_and_join(self) -> None:
        local = self.transport.local_node
        responses = self._ping_all()
        # 1) an active master already exists → join it
        active_master_ids = {r["master_id"] for r in responses
                             if r.get("master_id")} - {local.node_id}
        if active_master_ids:
            by_id = {r["node_id"]: self._node_from_ping(r)
                     for r in responses}
            master_id = sorted(active_master_ids)[0]
            master = by_id.get(master_id)
            if master is None:
                for r in responses:
                    if r.get("master_id") == master_id:
                        # the master itself didn't answer our ping; join via
                        # any node that knows it? → retry next round
                        return
            if master is not None:
                self._election_winner = master_id
                self._send_join(master)
                return
        # 2) full election among master-eligible candidates
        candidates = {local.node_id: local} if local.master_eligible else {}
        for r in responses:
            n = self._node_from_ping(r)
            if n.master_eligible:
                candidates[n.node_id] = n
        if len(candidates) < self.min_master_nodes:
            return                               # not enough nodes yet
        winner_id = sorted(candidates)[0]        # ElectMasterService ordering
        self._election_winner = winner_id
        if winner_id == local.node_id:
            # Do NOT take mastership on ping-knowledge alone: peers may
            # have settled on another winner (their ping round missed us),
            # and committing a 1-node master state here creates a
            # permanent split-brain (nobody pings a settled master again).
            # Like NodeJoinController.waitToBeElectedAsMaster, wait until a
            # quorum of peers has actually SENT us join votes — the
            # _handle_join vote path elects when votes reach
            # min_master_nodes. Only a true single-node quorum elects
            # immediately.
            if self.min_master_nodes <= 1:
                self._become_master()
        else:
            self._send_join(candidates[winner_id])

    def _send_join(self, master: DiscoveryNode) -> None:
        local = self.transport.local_node
        self.transport.submit_request(
            master, JOIN_ACTION,
            {"node": {"node_id": local.node_id, "name": local.name,
                      "host": local.address.host, "port": local.address.port,
                      "attributes": dict(local.attributes),
                      "version": local.version}},
            timeout=5.0)
        # wait for the resulting publish to land (we appear in state)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            st = self.cluster_service.state()
            if st.master_node_id == master.node_id and \
                    local.node_id in st.nodes:
                return
            time.sleep(0.01)

    def _become_master(self, extra_joiners: list[DiscoveryNode] = ()) -> None:
        local = self.transport.local_node
        joiners = list(extra_joiners)

        def update(state: ClusterState) -> ClusterState:
            nodes = dict(state.nodes)
            nodes[local.node_id] = local
            for j in joiners:
                nodes[j.node_id] = j
            if state.master_node_id == local.node_id:
                if all(j.node_id in state.nodes for j in joiners):
                    return state                 # genuinely nothing new
                # already master but a vote batch carried NEW joiners —
                # dropping them would orphan nodes that think they joined
                return self.allocation.reroute(
                    state.with_(nodes=nodes), "joiners while master")
            new = state.with_(master_node_id=local.node_id, nodes=nodes,
                              blocks=state.blocks - {NO_MASTER_BLOCK})
            if self.gateway_fn is not None and not new.indices:
                new = self.gateway_fn(new)
            return self.allocation.reroute(new, "elected as master")

        self.cluster_service.submit_state_update(
            "zen-disco-elected-as-master", update, priority=URGENT)

    # ---- inbound handlers --------------------------------------------------

    def _handle_ping(self, request: dict, source) -> dict:
        local = self.transport.local_node
        state = self.cluster_service.state()
        return {"cluster_name": self.cluster_name,
                "node_id": local.node_id, "name": local.name,
                "host": local.address.host, "port": local.address.port,
                "attributes": dict(local.attributes),
                "version": local.version,
                "master_id": state.master_node_id}

    def _handle_join(self, request: dict, channel) -> None:
        """NodeJoinController: as master, add the node; while electing,
        accumulate joins as votes until quorum."""
        n = request["node"]
        joiner = DiscoveryNode(
            n["node_id"], n["name"], TransportAddress(n["host"], n["port"]),
            attributes=tuple(sorted(n.get("attributes", {}).items())),
            version=n.get("version", 0))
        local = self.transport.local_node
        state = self.cluster_service.state()
        if state.master_node_id == local.node_id:
            def update(st: ClusterState) -> ClusterState:
                nodes = dict(st.nodes)
                nodes[joiner.node_id] = joiner
                if joiner.node_id in st.nodes and \
                        st.nodes[joiner.node_id].address == joiner.address:
                    # Already a member — but a re-join means the joiner
                    # never RECEIVED the state that added it (its initial
                    # publish timed out). A no-op here would deadlock: the
                    # joiner polls for a state that will never be sent
                    # again. Touch the version so the publish delivers the
                    # full state to it (NodeJoinController re-publishes on
                    # existing-node joins for the same reason).
                    return st.with_(nodes=nodes)
                return self.allocation.reroute(
                    self.allocation.reset_failed_counters(
                        st.with_(nodes=nodes)),
                    f"node joined [{joiner.name}]")
            fut = self.cluster_service.submit_state_update(
                f"zen-disco-join [{joiner.name}]", update, priority=URGENT)
            fut.add_done_callback(
                lambda f: channel.send_response({"ok": True})
                if f.exception() is None else channel.send_failure(
                    f.exception()))
            return
        if state.master_node_id is None and local.master_eligible and \
                self._election_winner == local.node_id:
            # election in progress AND our own ping round agrees we are
            # the best candidate: count the join as a vote — but only
            # MASTER-ELIGIBLE joiners count toward minimum_master_nodes
            # (ElectMasterService counts master nodes only), and votes
            # expire so dead electors can't satisfy a later quorum.
            # While we believe someone ELSE should win, reject instead:
            # counting votes while simultaneously voting elsewhere lets
            # two nodes assemble overlapping quorums (split-brain).
            now = time.monotonic()
            with self._votes_lock:
                self._pending_joins[joiner.node_id] = (joiner, now)
                live = {nid: (n, ts)
                        for nid, (n, ts) in self._pending_joins.items()
                        if now - ts < self.JOIN_VOTE_TTL}
                self._pending_joins = live
                votes = sum(1 for n, _ in live.values()
                            if n.master_eligible) + 1          # + self
                joiners = [n for n, _ in live.values()]
                elect = votes >= self.min_master_nodes
                if elect:
                    self._pending_joins = {}
            if elect:
                self._become_master(joiners)
                channel.send_response({"ok": True})
                return
        channel.send_failure(NotTheMasterError(
            f"[{local.name}] is not the master"))

    def _handle_leave(self, request: dict, source) -> dict:
        self._remove_node(request["node_id"], "node left (shutdown)")
        return {}

    # ---- failure paths -----------------------------------------------------

    def _on_node_failure(self, node: DiscoveryNode) -> None:
        self._remove_node(node.node_id, "fault detection ping failures")

    def _remove_node(self, node_id: str, reason: str) -> None:
        if not self.is_master():
            return

        def update(state: ClusterState) -> ClusterState:
            if node_id not in state.nodes:
                return state
            nodes = {nid: n for nid, n in state.nodes.items()
                     if nid != node_id}
            eligible = sum(1 for n in nodes.values() if n.master_eligible)
            if eligible < self.min_master_nodes:
                # quorum lost → step down (rejoin path runs via listener)
                return state.with_(
                    master_node_id=None, nodes=nodes,
                    blocks=state.blocks | {NO_MASTER_BLOCK})
            return self.allocation.reroute(
                state.with_(nodes=nodes), f"node removed: {reason}")

        try:
            self.cluster_service.submit_state_update(
                f"zen-disco-node-failed [{node_id}]", update,
                priority=URGENT)
        except RuntimeError:
            pass                                 # shutting down

    def _on_master_failure(self, master: DiscoveryNode) -> None:
        """Master stopped answering → drop it locally and rejoin
        (ZenDiscovery.handleMasterGone → rejoin :78,129)."""
        def task() -> None:
            current = self.cluster_service.state()
            if current.master_node_id != master.node_id:
                return
            # the dropped master must not pass the masterless publish
            # fence via a stale join target — its late commits are the
            # thing the fence rejects; the next ping round re-decides
            self._election_winner = None
            nodes = {nid: n for nid, n in current.nodes.items()
                     if nid != master.node_id}
            # local-only mutation: this node's view drops the master; the
            # join loop then re-elects. Keep the VERSION where it is — a
            # non-master running ahead of the master's version would make
            # the applier (gated on version > local) silently drop the
            # next publish.
            self.cluster_service.apply_new_state(current.with_(
                master_node_id=None, nodes=nodes,
                blocks=current.blocks | {NO_MASTER_BLOCK},
                version=current.version))
        try:
            self.cluster_service.run_task("zen-disco-master-failed", task,
                                          priority=URGENT)
        except RuntimeError:
            return
        self._ensure_join_thread()

    # ---- reacting to applied states ---------------------------------------

    def _cluster_changed(self, old: ClusterState, new: ClusterState) -> None:
        local_id = self.transport.local_node.node_id
        master_id = new.master_node_id
        if master_id is not None:
            # the winner tracks the master lineage we actually follow —
            # kept in sync here so the masterless publish fence compares
            # against the LAST followed master, however we came to
            # follow it (join, vote batch, or applied publish)
            self._election_winner = master_id
            with self._votes_lock:
                self._pending_joins = {}         # election settled
        if master_id == local_id:
            self.master_fd.stop()
            self.nodes_fd.update_nodes(new.nodes)
            self.nodes_fd.start()
        elif master_id is not None:
            self.nodes_fd.stop()
            if master_id != self._last_master_id:
                self.master_fd.restart(new.master_node)
        else:
            self.nodes_fd.stop()
            self.master_fd.stop()
            if self._running:
                self._ensure_join_thread()
        self._last_master_id = master_id
