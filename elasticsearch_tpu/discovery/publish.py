"""Two-phase diff-based cluster state publish.

Reference: core/discovery/zen/publish/PublishClusterStateAction.java:54,
138-169 — the master sends each node a DIFF when the node is known to hold
the previous state (or the FULL state otherwise, :167-169), waits for acks,
then sends COMMIT; nodes buffer the received state and only apply it on
commit. A node that cannot apply a diff answers with
IncompatibleClusterStateVersionException and the master resends the full
state (:155-163). Sends to all peers run in PARALLEL (the reference fans
out on the generic pool) so one unresponsive node costs one timeout, not
one per node; pending uncommitted states are bounded.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from elasticsearch_tpu.cluster.state import (
    ClusterState, IncompatibleClusterStateVersionError)
from elasticsearch_tpu.transport.service import (
    DiscoveryNode, RemoteTransportError, TransportService)

PUBLISH_ACTION = "internal:discovery/zen/publish"
COMMIT_ACTION = "internal:discovery/zen/publish/commit"

# max buffered uncommitted states per node (reference bounds its queue)
MAX_PENDING_STATES = 25


class FailedToCommitClusterStateError(Exception):
    """Raised when fewer than minimum_master_nodes master-eligible nodes
    acked a published state: the master must NOT apply it (the reference's
    Discovery.FailedToCommitClusterStateException discipline) — committing
    without a quorum is how a partitioned minority master builds a second
    state lineage that acks writes the healed cluster never saw."""


class PublishClusterStateAction:
    def __init__(self, transport: TransportService, cluster_service,
                 publish_timeout: float = 10.0):
        self.transport = transport
        self.cluster_service = cluster_service
        self.publish_timeout = publish_timeout
        # how many master-eligible acks (local node included) a state
        # needs before commit; discovery points this at its
        # minimum_master_nodes setting
        self.required_acks_fn = lambda: 1
        # the master this node is currently joining/voting for while it
        # has none (zen's election winner) — a masterless node must not
        # ack a publish from anyone else, or its ack props up a stale
        # master's commit quorum it never agreed to join
        self.expected_master_fn = lambda: None
        self._lock = threading.Lock()
        self._pending: OrderedDict[str, ClusterState] = OrderedDict()
        # last state each peer acked — governs diff vs full (the reference
        # tracks this via nodes' committed state versions)
        self._peer_state: dict[str, tuple[int, str]] = {}
        transport.register_request_handler(
            PUBLISH_ACTION, self._handle_publish, sync=True)
        transport.register_request_handler(
            COMMIT_ACTION, self._handle_commit, sync=True)

    # ---- master side -------------------------------------------------------

    def publish(self, new: ClusterState, old: ClusterState) -> None:
        """Fan the state out to every other node in `new` (parallel), then
        commit on the ackers (parallel) and apply locally."""
        local_id = self.transport.local_node.node_id
        targets = [n for nid, n in new.nodes.items() if nid != local_id]
        diff = new.diff_from(old)
        full = new.to_wire_dict()

        # phase 1: send (diff where possible), all nodes concurrently
        first = {}
        for node in targets:
            peer = self._peer_state.get(node.node_id)
            use_diff = peer == (old.version, old.state_uuid)
            payload = {"diff": diff} if use_diff else {"full": full}
            first[node.node_id] = (node, self.transport.send_request(
                node, PUBLISH_ACTION, payload, timeout=self.publish_timeout))
        retry = []
        acked: list[DiscoveryNode] = []
        for node, fut in first.values():
            try:
                fut.result(self.publish_timeout + 5.0)
                acked.append(node)
            except RemoteTransportError as e:
                if e.error_type == "IncompatibleClusterStateVersionError":
                    retry.append(node)
                else:
                    self._peer_state.pop(node.node_id, None)
            except Exception:                    # noqa: BLE001 — peer down
                self._peer_state.pop(node.node_id, None)
        # phase 1b: full-state resend to diff-incompatible nodes
        second = [(node, self.transport.send_request(
            node, PUBLISH_ACTION, {"full": full},
            timeout=self.publish_timeout)) for node in retry]
        for node, fut in second:
            try:
                fut.result(self.publish_timeout + 5.0)
                acked.append(node)
            except Exception:                    # noqa: BLE001 — peer down
                self._peer_state.pop(node.node_id, None)
        for node in acked:
            self._peer_state[node.node_id] = (new.version, new.state_uuid)

        # quorum gate: commit only with minimum_master_nodes
        # master-eligible acks (ourselves included) — otherwise the whole
        # update fails and nothing applies anywhere
        eligible_acks = sum(1 for n in acked if n.master_eligible) + \
            (1 if self.transport.local_node.master_eligible else 0)
        required = self.required_acks_fn()
        if eligible_acks < required:
            raise FailedToCommitClusterStateError(
                f"state v{new.version}: only {eligible_acks} of "
                f"{required} required master-eligible acks")

        # phase 2: commit — apply locally first (master applies what it
        # publishes even if some peers missed it; FD will handle them)
        self.cluster_service.apply_new_state(new)
        commits = [(node, self.transport.send_request(
            node, COMMIT_ACTION, {"uuid": new.state_uuid},
            timeout=self.publish_timeout)) for node in acked]
        for node, fut in commits:
            try:
                fut.result(self.publish_timeout + 5.0)
            except Exception:                    # noqa: BLE001 — peer down
                self._peer_state.pop(node.node_id, None)

    # ---- receiving side ----------------------------------------------------

    def _validate_publisher(self, sender_id: str) -> None:
        """A node accepts publishes/commits ONLY from (a) the master it
        follows, or (b) while masterless, the master it is currently
        joining (zen's election winner) — ZenDiscovery's from-current-
        master validation plus the join fence. Without (b), a node whose
        master-fd false-tripped would ack a healed stale master's
        publish, and that ack counts toward the stale commit quorum —
        two overlapping "quorums" and a second state lineage. The nack
        is also what tells the stale master to step down & rejoin."""
        local = self.cluster_service.state()
        if local.master_node_id is not None:
            if sender_id != local.master_node_id:
                raise ValueError(
                    f"rejecting publish from [{sender_id}]: already "
                    f"following [{local.master_node_id}]")
            return
        expected = self.expected_master_fn()
        if expected is None or sender_id != expected:
            # no join target at all also rejects: right after dropping a
            # master (winner cleared), that master's LATE commit must not
            # slip through the gap before the next ping round picks a
            # target. A legitimate new master's eager publish is nacked
            # once and accepted after this node joins it.
            raise ValueError(
                f"rejecting publish from [{sender_id}]: masterless, "
                f"joining [{expected}]")

    def _handle_publish(self, request: dict, source) -> dict:
        # validate the SENDER before touching the payload: a stale
        # master's diff would otherwise fail diff application first and
        # buy a wasted full-state resend round trip before the real nack
        self._validate_publisher(source.node_id)
        if "diff" in request:
            diff = request["diff"]
            base = self.cluster_service.state()
            state = ClusterState.apply_diff(base, diff)   # raises → resend
        else:
            state = ClusterState.from_wire_dict(request["full"])
        with self._lock:
            self._pending[state.state_uuid] = state
            while len(self._pending) > MAX_PENDING_STATES:
                self._pending.popitem(last=False)
        return {"version": state.version}

    def _handle_commit(self, request: dict, source) -> dict:
        with self._lock:
            state = self._pending.pop(request["uuid"], None)
        if state is None:
            raise IncompatibleClusterStateVersionError(
                f"no pending state {request['uuid']}")
        # re-validate at commit time: the state may have been buffered
        # before this node switched masters (fd dropped the old one
        # mid-publish), and a deposed master's late commit must not flip
        # us back onto its dead lineage — _pending outlives the switch
        self._validate_publisher(source.node_id)
        self.cluster_service.apply_published_state(state).result(30.0)
        return {}
