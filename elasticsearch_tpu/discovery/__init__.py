"""Discovery — membership, master election, state publish, fault detection.

Reference: core/discovery/zen/ — ZenDiscovery.java:76 (election/join/rejoin),
publish/PublishClusterStateAction.java (two-phase diff publish),
fd/{MasterFaultDetection,NodesFaultDetection}.java (mutual liveness pings),
elect/ElectMasterService.java (min_master_nodes quorum + ordered election).
"""

from elasticsearch_tpu.discovery.zen import ZenDiscovery
from elasticsearch_tpu.discovery.publish import PublishClusterStateAction
from elasticsearch_tpu.discovery.fd import (
    MasterFaultDetection, NodesFaultDetection)

__all__ = ["ZenDiscovery", "PublishClusterStateAction",
           "MasterFaultDetection", "NodesFaultDetection"]
