"""Fault detection — mutual liveness pings.

Reference: core/discovery/zen/fd/ — MasterFaultDetection.java (every node
pings its master; on N consecutive failures it notifies listeners → rejoin)
and NodesFaultDetection.java (the master pings every node; on failure the
node is removed from the cluster state). Wired in ZenDiscovery.java:97-98,
177-181. Ping handlers validate identity: a ping for a node id that is no
longer who we think it is fails fast (ThisIsNotTheMasterYouAreLookingForException).
"""

from __future__ import annotations

import threading

from elasticsearch_tpu.transport.service import DiscoveryNode, TransportService

MASTER_PING_ACTION = "internal:discovery/zen/fd/master_ping"
NODE_PING_ACTION = "internal:discovery/zen/fd/ping"

# remote error types that mean "the peer answered and said NO" — identity
# facts, not liveness flakes; both fault detectors skip the retry budget
# for them (the reference fails fast on these too instead of re-pinging)
_REJECTION_TYPES = ("NotTheMasterError", "NodeNotPartOfClusterError")


def _is_rejection(e: Exception) -> bool:
    return getattr(e, "error_type", None) in _REJECTION_TYPES


class _Pinger(threading.Thread):
    def __init__(self, name: str, interval: float, fn):
        super().__init__(daemon=True, name=name)
        self._interval = interval
        self._fn = fn
        self._stop = threading.Event()

    def run(self):
        while not self._stop.wait(self._interval):
            try:
                self._fn()
            except Exception:                    # noqa: BLE001 — keep pinging
                pass

    def stop(self):
        self._stop.set()


class MasterFaultDetection:
    """Runs on every non-master node; pings the master."""

    def __init__(self, transport: TransportService, interval: float = 0.5,
                 timeout: float = 1.0, retries: int = 3):
        self.transport = transport
        self.interval = interval
        self.timeout = timeout
        self.retries = retries
        self.on_master_failure = None            # callback(master_node)
        self._master: DiscoveryNode | None = None
        self._failures = 0
        self._pinger: _Pinger | None = None
        transport.register_request_handler(
            MASTER_PING_ACTION, self._handle_ping, executor="same",
            sync=True)
        self._is_master_fn = lambda: False       # set by discovery

    def restart(self, master: DiscoveryNode | None) -> None:
        self.stop()
        self._master = master
        self._failures = 0
        if master is None or \
                master.node_id == self.transport.local_node.node_id:
            return
        self._pinger = _Pinger(
            f"masterFD[{master.name}]", self.interval, self._ping_once)
        self._pinger.start()

    def stop(self) -> None:
        if self._pinger is not None:
            self._pinger.stop()
            self._pinger = None

    def _ping_once(self) -> None:
        master = self._master
        if master is None:
            return
        try:
            self.transport.submit_request(
                master, MASTER_PING_ACTION,
                {"master_id": master.node_id,
                 "source_id": self.transport.local_node.node_id},
                timeout=self.timeout)
            self._failures = 0
        except Exception as e:                   # noqa: BLE001 — count it
            # an explicit "I am not the master" answer is a fact, not a
            # flake: rejoin NOW instead of burning the retry budget (the
            # reference's MasterFaultDetection retries only on timeouts)
            if _is_rejection(e):
                self._failures = self.retries
            else:
                self._failures += 1
            if self._failures >= self.retries:
                self.stop()
                if self.on_master_failure is not None:
                    self.on_master_failure(master)

    def _handle_ping(self, request: dict, source) -> dict:
        # verify we actually are the master this node believes in
        if request["master_id"] != self.transport.local_node.node_id or \
                not self._is_master_fn():
            raise NotTheMasterError(
                f"[{self.transport.local_node.name}] is not the master")
        return {"ok": True}


class NotTheMasterError(Exception):
    pass


class NodeNotPartOfClusterError(Exception):
    pass


class NodesFaultDetection:
    """Runs on the master; pings every other cluster node."""

    def __init__(self, transport: TransportService, interval: float = 0.5,
                 timeout: float = 1.0, retries: int = 3):
        self.transport = transport
        self.interval = interval
        self.timeout = timeout
        self.retries = retries
        self.on_node_failure = None              # callback(node)
        self._nodes: dict[str, DiscoveryNode] = {}
        self._failures: dict[str, int] = {}
        self._pinger: _Pinger | None = None
        self._lock = threading.Lock()
        transport.register_request_handler(
            NODE_PING_ACTION, self._handle_ping, executor="same", sync=True)
        # wired by discovery: the master id this node currently follows
        self._current_master_fn = lambda: None

    def update_nodes(self, nodes: dict[str, DiscoveryNode]) -> None:
        local = self.transport.local_node.node_id
        with self._lock:
            self._nodes = {nid: n for nid, n in nodes.items() if nid != local}
            self._failures = {nid: f for nid, f in self._failures.items()
                              if nid in self._nodes}

    def start(self) -> None:
        if self._pinger is None:
            self._pinger = _Pinger("nodesFD", self.interval, self._ping_all)
            self._pinger.start()

    def stop(self) -> None:
        if self._pinger is not None:
            self._pinger.stop()
            self._pinger = None
        with self._lock:
            self._failures.clear()

    def _ping_all(self) -> None:
        with self._lock:
            targets = list(self._nodes.values())
        for node in targets:
            try:
                self.transport.submit_request(
                    node, NODE_PING_ACTION,
                    {"node_id": node.node_id,
                     "master_id": self.transport.local_node.node_id},
                    timeout=self.timeout)
                with self._lock:
                    self._failures[node.node_id] = 0
            except Exception as e:               # noqa: BLE001 — count it
                with self._lock:
                    # a rejection ("I follow another master" / "wrong
                    # node id") trips immediately — this is how a stale
                    # master that healed back from a partition learns the
                    # cluster moved on within ONE ping interval, instead
                    # of serving a second state lineage for retries x
                    # timeout more seconds
                    self._failures[node.node_id] = self.retries \
                        if _is_rejection(e) \
                        else self._failures.get(node.node_id, 0) + 1
                    tripped = self._failures[node.node_id] >= self.retries
                    if tripped:
                        self._nodes.pop(node.node_id, None)
                if tripped and self.on_node_failure is not None:
                    self.on_node_failure(node)

    def _handle_ping(self, request: dict, source) -> dict:
        if request["node_id"] != self.transport.local_node.node_id:
            raise NodeNotPartOfClusterError("wrong node id")
        # A ping from a master we follow someone ELSE than must fail —
        # this is how a deposed master learns the cluster moved on. A
        # `current is None` answer stays ok: at startup the master pings
        # while its join-publish to us is still in flight, and rejecting
        # would evict-and-rejoin-churn the joiner. The stale-member case
        # (node that never received its join-publish) is healed by the
        # join handler instead, which re-publishes on duplicate joins.
        current = self._current_master_fn()
        if current is not None and current != request.get("master_id"):
            raise NodeNotPartOfClusterError(
                f"ping from [{request.get('master_id')}] but current master "
                f"is [{current}]")
        return {"ok": True}
