"""Transport — the distributed communication backend.

Reference: core/transport/ — `TransportService` (TransportService.java)
request/response RPC over named actions; `NettyTransport`
(netty/NettyTransport.java:142) length-framed binary TCP; `LocalTransport`
(local/LocalTransport.java) in-process seam used by the whole test strategy.

TPU-native stance (SURVEY.md §2.2): this layer is the *control plane* —
cluster state publish, replication verbs, recovery streams, admin fan-out.
The query *data plane* inside a slice rides ICI collectives
(parallel/distributed.py shard_map programs), not per-shard RPC.
"""

from elasticsearch_tpu.transport.stream import StreamInput, StreamOutput
from elasticsearch_tpu.transport.service import (
    TransportService, TransportException, ActionNotFoundError,
    ConnectTransportError, ReceiveTimeoutError, RemoteTransportError,
    NodeDisconnectedError, TransportAddress, DiscoveryNode,
)
from elasticsearch_tpu.transport.local import LocalTransport, LocalTransportHub
from elasticsearch_tpu.transport.tcp import TcpTransport

__all__ = [
    "StreamInput", "StreamOutput", "TransportService", "TransportException",
    "ActionNotFoundError", "ConnectTransportError", "ReceiveTimeoutError",
    "RemoteTransportError", "NodeDisconnectedError", "TransportAddress",
    "DiscoveryNode", "LocalTransport", "LocalTransportHub", "TcpTransport",
]
