"""TransportService — request/response RPC over named actions.

Reference: core/transport/TransportService.java — handler registry
(`registerRequestHandler`), `sendRequest` with timeout handling
(TimeoutHandler), response-handler table keyed by request id, tracer hook
(`transport.tracer.include`), and the local-node shortcut. Payloads always
round-trip through the wire codec (stream.py) even in-process, so the
LocalTransport test seam exercises the same serialization as TCP —
mirroring how LocalTransport.java still serializes messages.
"""

from __future__ import annotations

import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable

from elasticsearch_tpu.transport.stream import (
    CURRENT_VERSION, StreamInput, StreamOutput)

#: fault-injection verdict: swallow the message entirely. Shared with the
#: transport-level seams (local.py re-exports it; tcp.py compares to the
#: same literal) so one scheme vocabulary covers both layers.
DROP = "drop"
#: fault-injection verdict constructors — a rule may also return
#: ("duplicate", n) to deliver 1+n copies, or ("reorder", jitter_s) to
#: hold the message and release it after the jitter (later messages pass
#: it, which is what reordering IS on an ordered transport).
DUPLICATE = "duplicate"
REORDER = "reorder"


class TransportException(Exception):
    pass


class ActionNotFoundError(TransportException):
    pass


class ConnectTransportError(TransportException):
    pass


class NodeDisconnectedError(ConnectTransportError):
    pass


class ReceiveTimeoutError(TransportException):
    pass


class RemoteTransportError(TransportException):
    """Failure raised by the remote handler; carries the remote error type."""

    def __init__(self, node_name: str, action: str, error_type: str,
                 reason: str):
        super().__init__(f"[{node_name}][{action}] {error_type}: {reason}")
        self.node_name = node_name
        self.action = action
        self.error_type = error_type
        self.reason = reason


@dataclass(frozen=True)
class TransportAddress:
    host: str
    port: int

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass(frozen=True)
class DiscoveryNode:
    """Reference: core/cluster/node/DiscoveryNode.java — id, name, address,
    attributes (data/master roles), wire version, build hash (1_000_100+,
    the Build.java analog surfaced in nodes info)."""
    node_id: str
    name: str
    address: TransportAddress
    attributes: tuple = ()
    version: int = CURRENT_VERSION
    build: str = ""

    @property
    def master_eligible(self) -> bool:
        return dict(self.attributes).get("master", "true") == "true"

    @property
    def data_node(self) -> bool:
        return dict(self.attributes).get("data", "true") == "true"

    def to_wire(self, out: StreamOutput) -> None:
        out.write_string(self.node_id)
        out.write_string(self.name)
        out.write_string(self.address.host)
        out.write_int(self.address.port)
        out.write_value(dict(self.attributes))
        out.write_vint(self.version)
        # gated field (StreamInput.java:58 pattern): both sides agreed on
        # min(local, remote) for this stream, so a 1_000_099 peer neither
        # writes nor expects the build hash
        if out.version >= 1_000_100:
            out.write_string(self.build)

    @staticmethod
    def from_wire(inp: StreamInput) -> "DiscoveryNode":
        node_id = inp.read_string()
        name = inp.read_string()
        address = TransportAddress(inp.read_string(), inp.read_int())
        attributes = tuple(sorted(inp.read_value().items()))
        version = inp.read_vint()
        build = inp.read_string() if inp.version >= 1_000_100 else ""
        return DiscoveryNode(node_id=node_id, name=name, address=address,
                             attributes=attributes, version=version,
                             build=build)


class TransportChannel:
    """Reply channel handed to request handlers (TransportChannel.java)."""

    def __init__(self, service: "TransportService", source: DiscoveryNode,
                 request_id: int, action: str):
        self._service = service
        self.source_node = source
        self.request_id = request_id
        self.action = action
        self._done = False
        # the Task registered for this request (TaskManager wiring);
        # unregistered when the reply goes out — the task's lifetime IS
        # the request's lifetime
        self.task = None

    def send_response(self, response: dict | None) -> None:
        if self._done:
            return
        self._done = True
        try:
            self._service._reply(self.source_node, self.request_id,
                                 response or {}, None)
        finally:
            self._service._finish_task(self)

    def send_failure(self, error: Exception) -> None:
        if self._done:
            return
        self._done = True
        try:
            self._service._reply(self.source_node, self.request_id, None,
                                 error)
        finally:
            self._service._finish_task(self)


@dataclass
class _RequestHandler:
    action: str
    handler: Callable                       # (request: dict, channel) -> None
    executor: str = "generic"               # "same" = run on delivery thread


@dataclass
class _ResponseContext:
    future: Future
    node: DiscoveryNode
    action: str
    timer: threading.Timer | None = None
    sent_at: float = field(default_factory=time.monotonic)


class TransportService:
    """One per node. Owns the handler registry and in-flight request table;
    delegates byte movement to a Transport (local.py / tcp.py)."""

    def __init__(self, transport, local_node_factory, executor=None,
                 thread_pool=None):
        """`local_node_factory(bound_address) -> DiscoveryNode` — the node
        identity depends on the port the transport binds. When the node's
        :class:`~elasticsearch_tpu.common.threadpool.ThreadPool` is given,
        named-executor dispatch runs on its bounded pools (rejections
        propagate to the caller as transport failures — backpressure);
        otherwise ad-hoc unbounded pools serve tests/standalone use."""
        self.transport = transport
        self._handlers: dict[str, _RequestHandler] = {}
        self._responses: dict[int, _ResponseContext] = {}
        self._request_id = 0
        self._lock = threading.Lock()
        self._executor = executor or ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="transport")
        self._owns_executor = executor is None
        self.thread_pool = thread_pool
        # Named per-workload pools (ThreadPool.java:70-129: index/bulk/
        # search/management...). Handlers that BLOCK on further RPCs (e.g.
        # a primary waiting for replica acks) must not share a pool with
        # the handlers they wait on, or two nodes writing to each other
        # deadlock when one pool saturates.
        self._pools: dict[str, ThreadPoolExecutor] = {}
        self._pools_lock = threading.Lock()
        self.tracers: list[Callable[[str, str, str], None]] = []
        # Service-level fault-injection seam (the MockTransportService
        # analog, one layer ABOVE the byte mover so it applies uniformly
        # to LocalTransport and TcpTransport): rule(addr, action) →
        # None | DROP | delay-seconds | ("duplicate", n) |
        # ("reorder", jitter-seconds). Evaluated on every outbound
        # request and response ("<response>" action, matching the
        # transport-level seams). Installed by testing_disruption
        # schemes; None in production.
        self.outbound_rule: Callable | None = None
        # TaskManager (tasks/manager.py), set by the node: every inbound
        # request registers a task, every outbound request carries the
        # current task's id as the parent link. None → no accounting
        # (standalone transports in unit tests).
        self.task_manager = None
        self._closed = False
        transport.bind(self)
        self.local_node: DiscoveryNode = local_node_factory(
            transport.bound_address())

    # ---- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with self._lock:
            pending = list(self._responses.values())
            self._responses.clear()
        for ctx in pending:
            if ctx.timer:
                ctx.timer.cancel()
            if not ctx.future.done():
                ctx.future.set_exception(
                    NodeDisconnectedError("transport closed"))
        self.transport.close()
        if self._owns_executor:
            self._executor.shutdown(wait=False, cancel_futures=True)
        with self._pools_lock:
            for pool in self._pools.values():
                pool.shutdown(wait=False, cancel_futures=True)
            self._pools.clear()

    # ---- registry ----------------------------------------------------------

    def register_request_handler(self, action: str, handler,
                                 executor: str = "generic",
                                 sync: bool = False) -> None:
        """`handler(request: dict, channel: TransportChannel)`; with
        `sync=True`, `handler(request: dict, source: DiscoveryNode) -> dict`
        and the response/failure is sent automatically."""
        if sync:
            inner = handler

            def handler(request, channel, _fn=inner):
                try:
                    channel.send_response(_fn(request, channel.source_node))
                except Exception as e:          # noqa: BLE001 — crosses RPC
                    channel.send_failure(e)
        self._handlers[action] = _RequestHandler(action, handler, executor)

    # ---- outbound ----------------------------------------------------------

    def send_request(self, node: DiscoveryNode, action: str, request: dict,
                     timeout: float | None = None) -> Future:
        """Returns a Future resolving to the response dict."""
        fut: Future = Future()
        if self._closed:
            fut.set_exception(NodeDisconnectedError("transport closed"))
            return fut
        with self._lock:
            self._request_id += 1
            rid = self._request_id
            ctx = _ResponseContext(fut, node, action)
            self._responses[rid] = ctx
        self._trace("send_request", action, node.node_id)
        if self.task_manager is not None:
            # parent-task propagation (TaskId in the request envelope):
            # the receiver links its task under ours, making the fan-out
            # one visible tree — and cancellable as one
            from elasticsearch_tpu.tasks import TASK_HEADER, current_task
            cur = current_task()
            if cur is not None:
                request = {**request, TASK_HEADER: cur.task_id}
        # trace-context propagation rides the same envelope: the
        # receiver re-roots its spans under ours, so one search yields
        # ONE cross-node span tree keyed by the coordinating task id
        from elasticsearch_tpu.observability.tracing import (
            TRACE_HEADER, wire_header)
        trace_hdr = wire_header()
        if trace_hdr is not None:
            request = {**request, TRACE_HEADER: trace_hdr}
        if timeout is not None:
            ctx.timer = threading.Timer(timeout, self._on_timeout, (rid,))
            ctx.timer.daemon = True
            ctx.timer.start()
        out = StreamOutput(min(self.local_node.version, node.version))
        out.write_value(request)
        payload = out.bytes()
        try:
            self._ruled_send(
                node.address, action,
                lambda: self.transport.send_request(node, rid, action,
                                                    payload))
        except Exception as e:                  # noqa: BLE001 — connect errors
            self._complete(rid, None, e if isinstance(e, TransportException)
                           else ConnectTransportError(str(e)))
        return fut

    def submit_request(self, node, action, request, timeout=None) -> dict:
        """Blocking convenience (TransportFuture.txGet analog)."""
        return self.send_request(node, action, request, timeout).result(
            timeout=None if timeout is None else timeout + 5.0)

    # ---- inbound (called by the Transport impl) ----------------------------

    def on_request(self, source: DiscoveryNode, request_id: int, action: str,
                   payload: bytes, wire_version: int) -> None:
        self._trace("recv_request", action, source.node_id)
        channel = TransportChannel(self, source, request_id, action)
        reg = self._handlers.get(action)
        if reg is None:
            channel.send_failure(ActionNotFoundError(action))
            return
        request = StreamInput(payload, wire_version).read_value()
        parent_task = None
        trace_hdr = None
        if isinstance(request, dict):
            from elasticsearch_tpu.observability.tracing import \
                TRACE_HEADER
            from elasticsearch_tpu.tasks import TASK_HEADER
            parent_task = request.pop(TASK_HEADER, None)
            trace_hdr = request.pop(TRACE_HEADER, None)
        if self.task_manager is not None:
            # register BEFORE dispatch so queue time on a saturated pool
            # is visible in the task list, and a ban that lands while the
            # request waits still cancels it before it runs a step
            channel.task = self.task_manager.register(
                action, description=f"requests[{source.name}]",
                parent_task_id=parent_task, task_type="transport")

        def run():
            from elasticsearch_tpu.observability.tracing import adopt
            from elasticsearch_tpu.tasks import use_task
            try:
                # spans record on the RECEIVING node's store, parented
                # under the sender's current span
                with use_task(channel.task), \
                        adopt(trace_hdr, self.local_node.node_id):
                    reg.handler(request, channel)
            except Exception as e:              # noqa: BLE001 — crosses RPC
                channel.send_failure(e)

        if reg.executor == "same" or self._closed:
            run()
        elif reg.executor == "generic":
            self._executor.submit(run)
        else:
            try:
                self._pool_for(reg.executor).submit(run)
            except Exception as e:              # noqa: BLE001 — rejection
                # bounded-pool rejection (EsRejectedExecutionError): the
                # caller gets the 429-class failure instead of unbounded
                # queueing — this IS the backpressure signal
                channel.send_failure(e)

    def on_response(self, request_id: int, payload: bytes | None,
                    error: tuple[str, str] | None,
                    wire_version: int) -> None:
        if error is not None:
            with self._lock:
                ctx = self._responses.get(request_id)
            name = ctx.node.name if ctx else "?"
            action = ctx.action if ctx else "?"
            self._complete(request_id, None,
                           RemoteTransportError(name, action, *error))
        else:
            self._complete(
                request_id, StreamInput(payload, wire_version).read_value(),
                None)

    def on_node_disconnected(self, node: DiscoveryNode) -> None:
        """Fail all in-flight requests targeting a dropped node
        (TransportService.java connection listener)."""
        with self._lock:
            dropped = [rid for rid, ctx in self._responses.items()
                       if ctx.node.node_id == node.node_id]
        for rid in dropped:
            self._complete(rid, None,
                           NodeDisconnectedError(f"[{node.name}] disconnected"))

    # ---- internals ---------------------------------------------------------

    def _finish_task(self, channel: "TransportChannel") -> None:
        """Unregister the request's task once its reply went out (or was
        dropped because the requester is gone) — the registry must never
        outlive the work it describes."""
        task, channel.task = channel.task, None
        if task is not None and self.task_manager is not None:
            self.task_manager.unregister(task)

    def _reply(self, to_node: DiscoveryNode, request_id: int,
               response: dict | None, error: Exception | None) -> None:
        self._trace("send_response", str(request_id), to_node.node_id)
        if error is not None:
            wire_err = (type(error).__name__, str(error))
            self._ruled_send(
                to_node.address, "<response>",
                lambda: self.transport.send_response(to_node, request_id,
                                                     None, wire_err))
        else:
            out = StreamOutput(min(self.local_node.version, to_node.version))
            out.write_value(response)
            payload = out.bytes()
            self._ruled_send(
                to_node.address, "<response>",
                lambda: self.transport.send_response(to_node, request_id,
                                                     payload, None))

    def _ruled_send(self, addr: "TransportAddress", action: str,
                    send: Callable[[], None]) -> None:
        """Apply the service-level fault rule, then move the bytes.
        Deferred sends (delay/reorder) fire on a timer and stay silent
        when the node died meanwhile — a resurrected stale send is the
        ghost-message class the disruption tests exist to rule out."""
        rule = self.outbound_rule
        verdict = rule(addr, action) if rule is not None else None
        if verdict is None:
            send()
            return
        if verdict == DROP:
            return
        if isinstance(verdict, (int, float)):
            if verdict <= 0:
                send()
                return
            self._deferred_send(float(verdict), send)
            return
        if isinstance(verdict, tuple) and len(verdict) == 2:
            kind, arg = verdict
            if kind == DUPLICATE:
                send()
                for _ in range(max(int(arg), 0)):
                    send()
                return
            if kind == REORDER:
                self._deferred_send(max(float(arg), 0.0), send)
                return
        raise ValueError(f"unknown fault verdict {verdict!r}")

    def _deferred_send(self, delay: float, send: Callable[[], None]) -> None:
        def fire():
            if self._closed:
                return
            try:
                send()
            except (OSError, TransportException):
                pass                             # target gone meanwhile
        t = threading.Timer(delay, fire)
        t.daemon = True
        t.start()

    def _pool_for(self, name: str):
        if self.thread_pool is not None:
            return self.thread_pool.executor(name)
        with self._pools_lock:
            pool = self._pools.get(name)
            if pool is None:
                pool = ThreadPoolExecutor(
                    max_workers=8, thread_name_prefix=f"transport-{name}")
                self._pools[name] = pool
            return pool

    def _complete(self, request_id: int, response: dict | None,
                  error: Exception | None) -> None:
        with self._lock:
            ctx = self._responses.pop(request_id, None)
        if ctx is None:
            return                               # late response after timeout
        if ctx.timer:
            ctx.timer.cancel()
        if ctx.future.done():
            return
        if error is not None:
            ctx.future.set_exception(error)
        else:
            ctx.future.set_result(response)

    def _on_timeout(self, request_id: int) -> None:
        with self._lock:
            ctx = self._responses.get(request_id)
        if ctx is None:
            return
        elapsed = time.monotonic() - ctx.sent_at
        self._complete(
            request_id, None,
            ReceiveTimeoutError(
                f"[{ctx.node.name}][{ctx.action}] request timed out after "
                f"{elapsed * 1e3:.0f}ms"))

    def _trace(self, event: str, action: str, node_id: str) -> None:
        for t in self.tracers:
            t(event, action, node_id)


def random_node_id() -> str:
    return uuid.uuid4().hex[:20]
