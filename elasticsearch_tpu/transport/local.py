"""LocalTransport — in-process transport for tests and embedded clusters.

Reference: core/transport/local/LocalTransport.java — nodes in one JVM wired
through a static address registry; messages still serialized, delivered on a
worker pool. This is the seam that makes the entire distributed system
testable in one process (SURVEY.md §4: InternalTestCluster runs N full nodes
over LocalTransport), and it carries the disruption hooks
(test/test/transport/MockTransportService.java analog): an outbound rule
callback may DROP a message, DELAY it, or let it pass.
"""

from __future__ import annotations

import itertools
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

from elasticsearch_tpu.transport.service import (
    DROP, ConnectTransportError, DiscoveryNode, TransportAddress)

__all__ = ["DROP", "LocalTransport", "LocalTransportHub"]


class LocalTransportHub:
    """Shared address registry — one per in-process cluster
    (LocalTransport.java `transports` static map, scoped per test cluster
    so parallel clusters don't collide)."""

    _ports = itertools.count(9300)

    def __init__(self):
        self._transports: dict[TransportAddress, LocalTransport] = {}
        self._lock = threading.Lock()

    def register(self, t: "LocalTransport") -> TransportAddress:
        with self._lock:
            addr = TransportAddress("local", next(self._ports))
            self._transports[addr] = t
            return addr

    def unregister(self, addr: TransportAddress) -> None:
        with self._lock:
            self._transports.pop(addr, None)

    def lookup(self, addr: TransportAddress) -> Optional["LocalTransport"]:
        with self._lock:
            return self._transports.get(addr)

    def addresses(self) -> list[TransportAddress]:
        with self._lock:
            return list(self._transports)


class LocalTransport:
    """One per node. Delivery happens on the receiving node's worker pool so
    caller threads never run remote handlers inline (matching the async
    delivery of LocalTransport.java `workers`)."""

    def __init__(self, hub: LocalTransportHub):
        self.hub = hub
        self._service = None
        self._address: TransportAddress | None = None
        self._pool = ThreadPoolExecutor(max_workers=4,
                                        thread_name_prefix="local_transport")
        self._closed = False
        # Disruption hook: rule(to_address, action) -> None | DROP | float
        # (seconds of delay). Set by disruption schemes (test support).
        self.outbound_rule: Callable | None = None

    # ---- Transport interface ----------------------------------------------

    def bind(self, service) -> None:
        self._service = service
        self._address = self.hub.register(self)

    def bound_address(self) -> TransportAddress:
        return self._address

    def close(self) -> None:
        self._closed = True
        self.hub.unregister(self._address)
        self._pool.shutdown(wait=False, cancel_futures=True)

    def send_request(self, node: DiscoveryNode, request_id: int, action: str,
                     payload: bytes) -> None:
        target, delay = self._ruled_lookup(node.address, action)
        if target is None:
            return                              # dropped by disruption rule
        version = min(self._service.local_node.version, node.version)
        source = self._service.local_node
        target._deliver(
            lambda: target._service.on_request(source, request_id, action,
                                               payload, version),
            delay=delay)

    def send_response(self, node: DiscoveryNode, request_id: int,
                      payload: bytes | None, error) -> None:
        # Responses ride the same disruption rules (a partition cuts both
        # directions; NetworkPartition.java severs request and response).
        target, delay = self._ruled_lookup(node.address, "<response>",
                                           raise_on_missing=False)
        if target is None:
            return
        version = min(self._service.local_node.version, node.version)
        target._deliver(
            lambda: target._service.on_response(request_id, payload, error,
                                                version),
            delay=delay)

    # ---- internals ---------------------------------------------------------

    def _ruled_lookup(self, addr: TransportAddress, action: str,
                      raise_on_missing: bool = True):
        """→ (target transport | None, delay seconds | None)."""
        if self._closed:
            raise ConnectTransportError("transport closed")
        rule = self.outbound_rule
        delay = None
        if rule is not None:
            verdict = rule(addr, action)
            if verdict == DROP:
                return None, None
            if isinstance(verdict, (int, float)) and verdict > 0:
                delay = float(verdict)
        target = self.hub.lookup(addr)
        if target is None or target._closed:
            if raise_on_missing:
                raise ConnectTransportError(f"no node at {addr}")
            return None, None
        return target, delay

    def _deliver(self, fn, delay: float | None = None) -> None:
        """Run `fn` on this node's worker pool; with `delay`, schedule the
        dispatch after the timer fires (NetworkDelays disruption). The
        delay lives HERE so no wrapper object has to mirror transport
        attributes for deferred sends."""
        if self._closed:
            return
        if delay:
            t = threading.Timer(delay, self._deliver, (fn,))
            t.daemon = True
            t.start()
            return
        try:
            self._pool.submit(fn)
        except RuntimeError:
            pass                                # pool shut down during close
