"""TcpTransport — length-framed binary RPC over real sockets.

Reference: core/transport/netty/NettyTransport.java:142 — 'E','S' marker +
4-byte length framing (NettyHeader.java, SizeHeaderFrameDecoder.java),
request/response status byte, request-id correlation, per-node channel
reuse (:871 `connectToNode`), version negotiation via min(local, remote)
on each frame. Threading: an accept loop + one reader thread per inbound
connection replaces the Netty event loop; handler dispatch happens on the
TransportService executor, matching the reference's worker offload.

Two NettyTransport disciplines carried over:
* optional frame compression (`transport.tcp.compress`, the LZF-optional
  bit of the reference's status byte — zlib here) — a flags byte after
  the marker, so each frame states whether its body is compressed;
* per-traffic-class outbound channels (connectToNode :871 opens
  recovery/bulk/reg/state/ping channel groups): the outbound socket is
  keyed by (address, class-of-action), so a bulk or recovery stream
  can't head-of-line-block pings or cluster-state publishes.

Frame layout: b"ET", 1 flags byte (bit0 = deflate), 4-byte big-endian
length, then the (possibly deflated) body:
  StreamOutput[ byte msg_type (0=req, 1=resp, 2=resp_error),
                long request_id, vint wire_version, then per type:
    req:        DiscoveryNode source, string action, bytes payload
    resp:       bytes payload
    resp_error: string error_type, string reason ]
"""

from __future__ import annotations

import socket
import struct
import threading
import zlib

from elasticsearch_tpu.transport.service import (
    ConnectTransportError, DiscoveryNode, TransportAddress)
from elasticsearch_tpu.transport.stream import (
    CURRENT_VERSION, StreamInput, StreamOutput)

_MARKER = b"ET"
_REQ, _RESP, _RESP_ERR = 0, 1, 2
_FLAG_COMPRESSED = 0x01
# compressing tiny frames (pings, acks) costs more than it saves
_COMPRESS_MIN_BYTES = 128

# action name → channel class, the reference's ChannelType routing
# (NettyTransport.connectToNode: recovery/bulk/reg/state/ping groups)
_CHANNEL_CLASSES = (
    ("internal:index/shard/recovery", "recovery"),
    ("indices:data/write", "bulk"),
    ("internal:discovery/zen/publish", "state"),
    ("cluster:monitor/state", "state"),
    ("internal:discovery/zen/fd", "ping"),
    ("internal:discovery/zen/unicast", "ping"),
)


def channel_class(action: str) -> str:
    for prefix, cls in _CHANNEL_CLASSES:
        if action.startswith(prefix):
            return cls
    return "reg"


class TcpTransport:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 publish_host: str | None = None,
                 compress: bool = False):
        self._host, self._want_port = host, port
        self.compress = compress
        # the address peers should dial (ref: `transport.publish_host` /
        # NetworkService publish resolution): binding to a wildcard must
        # not advertise the wildcard, which dials back to the PEER's own
        # loopback
        self._publish_host = publish_host
        self._service = None
        self._address: TransportAddress | None = None
        self._server: socket.socket | None = None
        self._closed = False
        self._lock = threading.Lock()
        # outbound sockets keyed by (address, channel class)
        self._outbound: dict[tuple[TransportAddress, str],
                             socket.socket] = {}
        # reply channels keyed by (requester node_id, its request_id):
        # request ids are per-requester counters, so two clients' ids collide
        self._inbound_channels: dict[tuple[str, int], socket.socket] = {}
        # every accepted connection, so close() can sever them — a killed
        # node must not process frames already in flight on inbound socks
        self._inbound_socks: set[socket.socket] = set()
        # one writer lock per live socket — sendall releases the GIL between
        # chunks, so unserialized concurrent writers interleave frames
        self._write_locks: dict[int, threading.Lock] = {}
        self._threads: list[threading.Thread] = []
        # Disruption hook: rule(to_address, action) -> None | "drop" | float
        # (seconds of delay) — same seam LocalTransport exposes, so the
        # disruption schemes (testing_disruption.py) run over real sockets.
        self.outbound_rule = None

    # ---- Transport interface ----------------------------------------------

    def bind(self, service) -> None:
        self._service = service
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self._host, self._want_port))
        srv.listen(64)
        self._server = srv
        publish = self._publish_host or self._host
        if publish in ("0.0.0.0", "::", ""):
            publish = self._default_publish_host()
        self._address = TransportAddress(publish, srv.getsockname()[1])
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"tcp_accept[{self._address}]")
        t.start()
        self._threads.append(t)

    def bound_address(self) -> TransportAddress:
        return self._address

    @staticmethod
    def _default_publish_host() -> str:
        """Best routable local address when bound to a wildcard: the source
        address of an (unsent) UDP connect to a public IP, falling back to
        the hostname's resolution, then loopback."""
        try:
            probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                probe.connect(("8.8.8.8", 9))
                return probe.getsockname()[0]
            finally:
                probe.close()
        except OSError:
            pass
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            socks = list(self._outbound.values()) + \
                list(self._inbound_socks)
            self._outbound.clear()
            self._inbound_socks.clear()
            self._write_locks.clear()
        for s in socks:
            try:
                # shutdown unblocks reader threads parked in recv() so no
                # already-inflight frame gets dispatched after the kill
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass

    def _ruled(self, addr: TransportAddress, action: str,
               send) -> bool:
        """Apply the disruption rule; → True when the send was handled
        (dropped or deferred), False when the caller should send now."""
        rule = self.outbound_rule
        if rule is None:
            return False
        verdict = rule(addr, action)
        if verdict == "drop":
            return True
        if isinstance(verdict, (int, float)) and verdict > 0:
            def fire():
                # a node killed while the delay was pending must stay
                # silent (LocalTransport._deliver's _closed guard): a
                # resurrected stale send is exactly the ghost-message
                # class the disruption tests exist to rule out
                if self._closed:
                    return
                try:
                    send()
                except (OSError, ConnectTransportError):
                    pass                         # target gone meanwhile
            t = threading.Timer(float(verdict), fire)
            t.daemon = True
            t.start()
            return True
        return False

    def send_request(self, node: DiscoveryNode, request_id: int, action: str,
                     payload: bytes) -> None:
        if self._ruled(node.address, action,
                       lambda: self._do_send_request(node, request_id,
                                                     action, payload)):
            return
        self._do_send_request(node, request_id, action, payload)

    def _do_send_request(self, node: DiscoveryNode, request_id: int,
                         action: str, payload: bytes) -> None:
        # the ENVELOPE serializes at the negotiated version too — gated
        # fields inside DiscoveryNode.to_wire key off out.version
        wire_version = min(self._service.local_node.version, node.version)
        out = StreamOutput(wire_version)
        out.write_byte(_REQ)
        out.write_long(request_id)
        out.write_vint(wire_version)
        self._service.local_node.to_wire(out)
        out.write_string(action)
        out.write_bytes(payload)
        self._send_frame(node.address, out.bytes(), channel_class(action))

    def send_response(self, node: DiscoveryNode, request_id: int,
                      payload: bytes | None, error) -> None:
        # pop the reply channel BEFORE the disruption rule: a dropped
        # response must not leak the (node_id, request_id) → socket entry
        with self._lock:
            chan = self._inbound_channels.pop((node.node_id, request_id),
                                              None)
        if self._ruled(node.address, "<response>",
                       lambda: self._do_send_response(node, request_id,
                                                      payload, error, chan)):
            return
        self._do_send_response(node, request_id, payload, error, chan)

    def _do_send_response(self, node: DiscoveryNode, request_id: int,
                          payload: bytes | None, error,
                          chan: socket.socket | None = None) -> None:
        # response envelope serializes at the negotiated version, same
        # as the request path
        wire_version = min(self._service.local_node.version, node.version)
        out = StreamOutput(wire_version)
        if error is None:
            out.write_byte(_RESP)
            out.write_long(request_id)
            out.write_vint(wire_version)
            out.write_bytes(payload)
        else:
            out.write_byte(_RESP_ERR)
            out.write_long(request_id)
            out.write_vint(wire_version)
            out.write_string(error[0])
            out.write_string(error[1])
        # Prefer the inbound channel the request arrived on (the reference
        # replies on the request's channel); fall back to an outbound conn.
        if chan is not None:
            try:
                self._write_framed(chan, out.bytes())
                return
            except OSError:
                pass
        try:
            self._send_frame(node.address, out.bytes(), "reg")
        except ConnectTransportError:
            pass                                 # requester is gone

    # ---- socket plumbing ---------------------------------------------------

    def _send_frame(self, addr: TransportAddress, body: bytes,
                    cls: str = "reg") -> None:
        sock = self._connect(addr, cls)
        try:
            self._write_framed(sock, body)
        except OSError as e:
            with self._lock:
                self._outbound.pop((addr, cls), None)
                self._write_locks.pop(id(sock), None)
            raise ConnectTransportError(f"send to {addr} failed: {e}") from e

    def _write_framed(self, sock: socket.socket, body: bytes) -> None:
        flags = 0
        if self.compress and len(body) >= _COMPRESS_MIN_BYTES:
            body = zlib.compress(body, 6)
            flags |= _FLAG_COMPRESSED
        with self._lock:
            wl = self._write_locks.setdefault(id(sock), threading.Lock())
        with wl:
            sock.sendall(_MARKER + bytes([flags])
                         + struct.pack(">i", len(body)) + body)

    def _connect(self, addr: TransportAddress,
                 cls: str = "reg") -> socket.socket:
        if self._closed:
            # a killed node must not dial fresh connections: a handler
            # thread that outlived close() could otherwise ACK a write
            # whose replica fan-out was failed by that very close — the
            # promoted replica then misses an acked doc (chaos-matrix
            # find: master kill racing a bulk)
            raise ConnectTransportError("transport closed")
        key = (addr, cls)
        with self._lock:
            sock = self._outbound.get(key)
        if sock is not None:
            return sock
        try:
            sock = socket.create_connection((addr.host, addr.port),
                                            timeout=5.0)
        except OSError as e:
            raise ConnectTransportError(f"connect to {addr} failed: {e}") \
                from e
        sock.settimeout(None)
        with self._lock:
            existing = self._outbound.get(key)
            if existing is not None:
                sock.close()
                return existing
            self._outbound[key] = sock
        t = threading.Thread(target=self._read_loop, args=(sock,),
                             daemon=True, name=f"tcp_read[{addr}/{cls}]")
        t.start()
        self._threads.append(t)
        return sock

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            with self._lock:
                if self._closed:
                    conn.close()
                    continue
                self._inbound_socks.add(conn)
            t = threading.Thread(target=self._read_loop, args=(conn,),
                                 daemon=True, name="tcp_read[inbound]")
            t.start()
            self._threads.append(t)

    def _read_loop(self, sock: socket.socket) -> None:
        try:
            while not self._closed:
                header = self._read_exact(sock, 7)
                if header is None:
                    return
                if header[:2] != _MARKER:
                    return                       # corrupt stream: drop conn
                flags = header[2]
                size = struct.unpack(">i", header[3:])[0]
                body = self._read_exact(sock, size)
                if body is None:
                    return
                if flags & _FLAG_COMPRESSED:
                    try:
                        body = zlib.decompress(body)
                    except zlib.error:
                        return                   # corrupt stream: drop conn
                self._handle_frame(sock, body)
        except OSError:
            return
        finally:
            with self._lock:
                self._write_locks.pop(id(sock), None)
                self._inbound_socks.discard(sock)
            try:
                sock.close()
            except OSError:
                pass

    @staticmethod
    def _read_exact(sock: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    def _handle_frame(self, sock: socket.socket, body: bytes) -> None:
        inp = StreamInput(body)
        msg_type = inp.read_byte()
        request_id = inp.read_long()
        version = inp.read_vint()
        # everything after the version vint — including the envelope's
        # DiscoveryNode — parses at the declared stream version
        inp.version = version
        if msg_type == _REQ:
            source = DiscoveryNode.from_wire(inp)
            action = inp.read_string()
            payload = inp.read_bytes()
            with self._lock:
                self._inbound_channels[(source.node_id, request_id)] = sock
            self._service.on_request(source, request_id, action, payload,
                                     version)
        elif msg_type == _RESP:
            self._service.on_response(request_id, inp.read_bytes(), None,
                                      version)
        elif msg_type == _RESP_ERR:
            err = (inp.read_string(), inp.read_string())
            self._service.on_response(request_id, None, err, version)
