"""Version-conditional binary wire format.

Reference: core/common/io/stream/{StreamInput,StreamOutput}.java — hand-rolled
binary streams where every stream carries the remote node's wire version
(StreamInput.java:58 `setVersion`) so readers/writers can gate fields for
rolling-upgrade compatibility, plus a tagged `writeGenericValue` for
heterogeneous maps (StreamOutput `writeGenericValue`).

The codec is deliberately self-contained (no pickle — payloads cross real
sockets in TcpTransport, and unpickling remote bytes would be an RCE).
"""

from __future__ import annotations

import struct

# Wire version of this codec generation; bump when adding gated fields.
# Mirrors org.elasticsearch.Version ids (Version.java) in spirit: an int that
# both sides exchange during the handshake, min(local, remote) governs the
# stream (NettyTransport sets the stream version from the channel handshake).
# History:
#   1_000_099 — base codec generation (rounds 1-3)
#   1_000_100 — DiscoveryNode carries a `build` hash (gated: StreamInput
#               .java:58-style read guarded on the stream version)
V_1_0_99 = 1_000_099
CURRENT_VERSION = 1_000_100
MINIMUM_COMPATIBLE_VERSION = 1_000_000

_NULL = 0
_STRING = 1
_INT = 2
_LONG = 3
_FLOAT = 4
_DOUBLE = 5
_BOOL = 6
_BYTES = 7
_LIST = 8
_MAP = 9


class StreamOutput:
    """Append-only binary writer (StreamOutput.java analog)."""

    def __init__(self, version: int = CURRENT_VERSION):
        self.version = version
        self._parts: list[bytes] = []

    # ---- primitives --------------------------------------------------------

    def write_byte(self, b: int) -> None:
        self._parts.append(bytes((b & 0xFF,)))

    def write_bool(self, v: bool) -> None:
        self.write_byte(1 if v else 0)

    def write_int(self, v: int) -> None:
        self._parts.append(struct.pack(">i", v))

    def write_long(self, v: int) -> None:
        self._parts.append(struct.pack(">q", v))

    def write_double(self, v: float) -> None:
        self._parts.append(struct.pack(">d", v))

    def write_vint(self, v: int) -> None:
        """LEB128-style varint (StreamOutput.writeVInt)."""
        if v < 0:
            raise ValueError(f"negative vint {v}")
        while v >= 0x80:
            self._parts.append(bytes(((v & 0x7F) | 0x80,)))
            v >>= 7
        self._parts.append(bytes((v,)))

    def write_zlong(self, v: int) -> None:
        """Zigzag-encoded signed varint (writeZLong)."""
        self.write_vlong((v << 1) ^ (v >> 63) if v < 0 else v << 1)

    def write_vlong(self, v: int) -> None:
        self.write_vint(v)

    def write_bytes(self, b: bytes) -> None:
        self.write_vint(len(b))
        self._parts.append(b)

    def write_raw(self, b: bytes) -> None:
        self._parts.append(b)

    def write_string(self, s: str) -> None:
        self.write_bytes(s.encode("utf-8"))

    def write_optional_string(self, s: str | None) -> None:
        self.write_bool(s is not None)
        if s is not None:
            self.write_string(s)

    def write_string_list(self, items) -> None:
        self.write_vint(len(items))
        for s in items:
            self.write_string(s)

    # ---- tagged generic values (writeGenericValue) -------------------------

    def write_value(self, v) -> None:
        if v is None:
            self.write_byte(_NULL)
        elif isinstance(v, bool):                 # before int: bool⊂int in py
            self.write_byte(_BOOL)
            self.write_bool(v)
        elif isinstance(v, str):
            self.write_byte(_STRING)
            self.write_string(v)
        elif isinstance(v, int):
            if -(2**31) <= v < 2**31:
                self.write_byte(_INT)
                self.write_int(v)
            else:
                self.write_byte(_LONG)
                self.write_long(v)
        elif isinstance(v, float):
            self.write_byte(_DOUBLE)
            self.write_double(v)
        elif isinstance(v, (bytes, bytearray)):
            self.write_byte(_BYTES)
            self.write_bytes(bytes(v))
        elif isinstance(v, (list, tuple)):
            self.write_byte(_LIST)
            self.write_vint(len(v))
            for item in v:
                self.write_value(item)
        elif isinstance(v, dict):
            self.write_byte(_MAP)
            self.write_vint(len(v))
            for k, item in v.items():
                self.write_string(str(k))
                self.write_value(item)
        else:
            # numpy scalars and other number-likes degrade to float/int
            try:
                import numpy as np
                if isinstance(v, np.integer):
                    return self.write_value(int(v))
                if isinstance(v, np.floating):
                    return self.write_value(float(v))
                if isinstance(v, np.ndarray):
                    return self.write_value(v.tolist())
            except ImportError:
                pass
            raise TypeError(f"cannot serialize {type(v)!r} to wire")

    def bytes(self) -> bytes:
        return b"".join(self._parts)

    def __len__(self) -> int:
        return sum(len(p) for p in self._parts)


class StreamInput:
    """Binary reader over a bytes buffer (StreamInput.java analog)."""

    def __init__(self, data: bytes, version: int = CURRENT_VERSION):
        self._data = data
        self._pos = 0
        self.version = version

    def _take(self, n: int) -> bytes:
        if self._pos + n > len(self._data):
            raise EOFError(
                f"stream truncated: need {n} bytes at {self._pos}, "
                f"have {len(self._data)}")
        b = self._data[self._pos:self._pos + n]
        self._pos += n
        return b

    def read_byte(self) -> int:
        return self._take(1)[0]

    def read_bool(self) -> bool:
        return self.read_byte() != 0

    def read_int(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def read_long(self) -> int:
        return struct.unpack(">q", self._take(8))[0]

    def read_double(self) -> float:
        return struct.unpack(">d", self._take(8))[0]

    def read_vint(self) -> int:
        v = shift = 0
        while True:
            b = self.read_byte()
            v |= (b & 0x7F) << shift
            if not b & 0x80:
                return v
            shift += 7
            if shift > 70:
                raise ValueError("vint too long")

    def read_vlong(self) -> int:
        return self.read_vint()

    def read_zlong(self) -> int:
        v = self.read_vlong()
        return (v >> 1) ^ -(v & 1)

    def read_bytes(self) -> bytes:
        return self._take(self.read_vint())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def read_optional_string(self) -> str | None:
        return self.read_string() if self.read_bool() else None

    def read_string_list(self) -> list[str]:
        return [self.read_string() for _ in range(self.read_vint())]

    def read_value(self):
        tag = self.read_byte()
        if tag == _NULL:
            return None
        if tag == _STRING:
            return self.read_string()
        if tag == _INT:
            return self.read_int()
        if tag == _LONG:
            return self.read_long()
        if tag == _DOUBLE:
            return self.read_double()
        if tag == _FLOAT:
            return struct.unpack(">f", self._take(4))[0]
        if tag == _BOOL:
            return self.read_bool()
        if tag == _BYTES:
            return self.read_bytes()
        if tag == _LIST:
            return [self.read_value() for _ in range(self.read_vint())]
        if tag == _MAP:
            return {self.read_string(): self.read_value()
                    for _ in range(self.read_vint())}
        raise ValueError(f"unknown wire tag {tag}")

    def remaining(self) -> int:
        return len(self._data) - self._pos
