"""Reusable fault-injection schemes for in-process clusters.

Reference: test/test/disruption/ — ServiceDisruptionScheme implementations
(NetworkPartition, NetworkDisconnectPartition, NetworkUnresponsivePartition,
NetworkDelaysPartition, BlockClusterStateProcessing,
SlowClusterStateProcessing) applied to the InternalTestCluster. Here the
schemes install outbound rules on each node's LocalTransport (the same
seam MockTransportService uses in the reference), so any multi-node test
can compose partitions/delays declaratively:

    with NetworkPartition([n1], [n2, n3]).applied():
        ...cluster behavior under partition...
"""

from __future__ import annotations

import contextlib
import random
import time

from elasticsearch_tpu.transport.local import DROP


def _addr_of(node):
    return node.transport_service.local_node.address


class ServiceDisruptionScheme:
    """Base: install/remove outbound rules on the affected nodes."""

    def __init__(self):
        self._saved: list[tuple] = []

    def _nodes(self) -> list:
        raise NotImplementedError

    def _rule_for(self, node):
        """→ callable(addr, action) -> DROP | delay-seconds | None, or
        None when this node needs no rule."""
        raise NotImplementedError

    def apply(self) -> None:
        for node in self._nodes():
            transport = node.transport_service.transport
            self._saved.append((transport, transport.outbound_rule))
            prev = transport.outbound_rule
            mine = self._rule_for(node)

            def combined(addr, action, _prev=prev, _mine=mine):
                # DROP from ANY stacked scheme wins; otherwise the
                # longest delay applies (a partition stacked over a delay
                # must still cut traffic)
                verdicts = []
                for rule in (_prev, _mine):
                    if rule is None:
                        continue
                    v = rule(addr, action)
                    if v == DROP:
                        return DROP
                    if v is not None:
                        verdicts.append(v)
                delays = [v for v in verdicts
                          if isinstance(v, (int, float))]
                return max(delays) if delays else None
            transport.outbound_rule = combined

    def remove(self) -> None:
        # LIFO: overlapping schemes must unwind in reverse application
        # order or a stale snapshot clobbers a newer one
        for transport, prev in reversed(self._saved):
            transport.outbound_rule = prev
        self._saved.clear()

    # the reference's ServiceDisruptionScheme verb pair
    start_disrupting = apply
    stop_disrupting = remove

    @contextlib.contextmanager
    def applied(self):
        self.apply()
        try:
            yield self
        finally:
            self.remove()


class NetworkPartition(ServiceDisruptionScheme):
    """Two-sided partition: traffic between side A and side B is cut in
    BOTH directions (NetworkDisconnectPartition semantics — requests fail
    as dropped; our transport surfaces that as a timeout/connect error,
    covering the Unresponsive variant too)."""

    def __init__(self, side_a: list, side_b: list):
        super().__init__()
        self.side_a = list(side_a)
        self.side_b = list(side_b)

    def _nodes(self) -> list:
        return self.side_a + self.side_b

    def _rule_for(self, node):
        other = self.side_b if node in self.side_a else self.side_a
        cut = {_addr_of(n) for n in other}

        def rule(addr, action):
            return DROP if addr in cut else None
        return rule


# the reference ships disconnect and unresponsive as separate schemes;
# over LocalTransport both manifest as dropped frames
NetworkDisconnectPartition = NetworkPartition
NetworkUnresponsivePartition = NetworkPartition


class NetworkDelaysPartition(ServiceDisruptionScheme):
    """Cross-side traffic is DELAYED by a random interval in
    [min_delay, max_delay] seconds (NetworkDelaysPartition)."""

    def __init__(self, side_a: list, side_b: list,
                 min_delay: float = 0.1, max_delay: float = 0.5,
                 seed: int | None = None):
        super().__init__()
        self.side_a = list(side_a)
        self.side_b = list(side_b)
        self.min_delay = min_delay
        self.max_delay = max_delay
        self._rng = random.Random(seed)

    def _nodes(self) -> list:
        return self.side_a + self.side_b

    def _rule_for(self, node):
        other = self.side_b if node in self.side_a else self.side_a
        slow = {_addr_of(n) for n in other}

        def rule(addr, action):
            if addr in slow:
                return self._rng.uniform(self.min_delay, self.max_delay)
            return None
        return rule


class IsolateNode(NetworkPartition):
    """Cut one node off from the rest (the reference's common
    one-node-vs-majority construction)."""

    def __init__(self, node, rest: list):
        super().__init__([node], list(rest))


class BlockClusterStateProcessing(ServiceDisruptionScheme):
    """Drop cluster-state publish traffic TO one node — it keeps serving
    with a stale view (BlockClusterStateProcessing)."""

    PUBLISH_PREFIX = "internal:discovery/zen/publish"

    def __init__(self, blocked_node, publishers: list):
        super().__init__()
        self.blocked = blocked_node
        self.publishers = list(publishers)

    def _nodes(self) -> list:
        return self.publishers

    def _rule_for(self, node):
        target = _addr_of(self.blocked)

        def rule(addr, action):
            if addr == target and action.startswith(self.PUBLISH_PREFIX):
                return DROP
            return None
        return rule


class SlowClusterStateProcessing(BlockClusterStateProcessing):
    """Delay (not drop) state publishes to one node
    (SlowClusterStateProcessing)."""

    def __init__(self, slow_node, publishers: list, delay_s: float = 0.5):
        super().__init__(slow_node, publishers)
        self.delay_s = delay_s

    def _rule_for(self, node):
        target = _addr_of(self.blocked)

        def rule(addr, action):
            if addr == target and action.startswith(self.PUBLISH_PREFIX):
                return self.delay_s
            return None
        return rule


def wait_until(predicate, timeout: float = 10.0,
               interval: float = 0.05) -> bool:
    """Poll helper for disruption tests (assertBusy analog)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()
