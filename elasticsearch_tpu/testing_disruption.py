"""Reusable fault-injection schemes for in-process clusters.

Reference: test/test/disruption/ — ServiceDisruptionScheme implementations
(NetworkPartition, NetworkDisconnectPartition, NetworkUnresponsivePartition,
NetworkDelaysPartition, BlockClusterStateProcessing,
SlowClusterStateProcessing) applied to the InternalTestCluster, plus the
MockTransportService message-granular capabilities and the
MockDirectoryWrapper disk-fault wrapper. Two injection seams:

* address-level schemes install outbound rules on each node's transport
  (LocalTransport/TcpTransport ``outbound_rule`` — drop/delay only);
* message-granular schemes install on the node's **TransportService**
  (``transport/service.py`` seam), which runs above the byte mover and
  therefore applies uniformly to both transports and supports the full
  verdict vocabulary: drop, delay, duplicate, reorder — per RPC action
  class, drawn from a seeded rng so any failure replays from its seed.

Disk faults (:class:`DiskFaultScheme`) inject IO errors and short writes
into translog appends/fsyncs and store/commit writes; the engine reacts
by self-failing → shard-failed → reallocation (never a wedged shard).

    with NetworkPartition([n1], [n2, n3]).applied():
        ...cluster behavior under partition...
"""

from __future__ import annotations

import contextlib
import random
import threading
import time

from elasticsearch_tpu.transport.service import DROP, DUPLICATE, REORDER


def _addr_of(node):
    return node.transport_service.local_node.address


class ServiceDisruptionScheme:
    """Base: install/remove outbound rules on the affected nodes."""

    #: where the rule lives: "transport" (address-level seam on the byte
    #: mover) or "service" (message-granular seam on TransportService)
    RULE_HOST = "transport"

    def __init__(self):
        self._saved: list[tuple] = []

    def _nodes(self) -> list:
        raise NotImplementedError

    def _rule_for(self, node):
        """→ callable(addr, action) -> DROP | delay-seconds |
        ("duplicate", n) | ("reorder", jitter) | None, or None when this
        node needs no rule (tuple verdicts only on the service seam)."""
        raise NotImplementedError

    def _host(self, node):
        ts = node.transport_service
        return ts if self.RULE_HOST == "service" else ts.transport

    def apply(self) -> None:
        for node in self._nodes():
            host = self._host(node)
            self._saved.append((host, host.outbound_rule))
            prev = host.outbound_rule
            mine = self._rule_for(node)

            def combined(addr, action, _prev=prev, _mine=mine):
                # DROP from ANY stacked scheme wins; a duplicate/reorder
                # verdict wins next (they are rare per-message draws);
                # otherwise the longest delay applies (a partition
                # stacked over a delay must still cut traffic)
                verdicts = []
                for rule in (_prev, _mine):
                    if rule is None:
                        continue
                    v = rule(addr, action)
                    if v == DROP:
                        return DROP
                    if v is not None:
                        verdicts.append(v)
                for v in verdicts:
                    if isinstance(v, tuple):
                        return v
                delays = [v for v in verdicts
                          if isinstance(v, (int, float))]
                return max(delays) if delays else None
            host.outbound_rule = combined

    def remove(self) -> None:
        # LIFO: overlapping schemes must unwind in reverse application
        # order or a stale snapshot clobbers a newer one
        for host, prev in reversed(self._saved):
            host.outbound_rule = prev
        self._saved.clear()

    # the reference's ServiceDisruptionScheme verb pair
    start_disrupting = apply
    stop_disrupting = remove

    @contextlib.contextmanager
    def applied(self):
        self.apply()
        try:
            yield self
        finally:
            self.remove()


class NetworkPartition(ServiceDisruptionScheme):
    """Two-sided partition: traffic between side A and side B is cut in
    BOTH directions (NetworkDisconnectPartition semantics — requests fail
    as dropped; our transport surfaces that as a timeout/connect error,
    covering the Unresponsive variant too)."""

    def __init__(self, side_a: list, side_b: list):
        super().__init__()
        self.side_a = list(side_a)
        self.side_b = list(side_b)

    def _nodes(self) -> list:
        return self.side_a + self.side_b

    def _rule_for(self, node):
        other = self.side_b if node in self.side_a else self.side_a
        cut = {_addr_of(n) for n in other}

        def rule(addr, action):
            return DROP if addr in cut else None
        return rule


# the reference ships disconnect and unresponsive as separate schemes;
# over LocalTransport both manifest as dropped frames
NetworkDisconnectPartition = NetworkPartition
NetworkUnresponsivePartition = NetworkPartition


class NetworkDelaysPartition(ServiceDisruptionScheme):
    """Cross-side traffic is DELAYED by a random interval in
    [min_delay, max_delay] seconds (NetworkDelaysPartition)."""

    def __init__(self, side_a: list, side_b: list,
                 min_delay: float = 0.1, max_delay: float = 0.5,
                 seed: int | None = None):
        super().__init__()
        self.side_a = list(side_a)
        self.side_b = list(side_b)
        self.min_delay = min_delay
        self.max_delay = max_delay
        self._rng = random.Random(seed)

    def _nodes(self) -> list:
        return self.side_a + self.side_b

    def _rule_for(self, node):
        other = self.side_b if node in self.side_a else self.side_a
        slow = {_addr_of(n) for n in other}

        def rule(addr, action):
            if addr in slow:
                return self._rng.uniform(self.min_delay, self.max_delay)
            return None
        return rule


class IsolateNode(NetworkPartition):
    """Cut one node off from the rest (the reference's common
    one-node-vs-majority construction)."""

    def __init__(self, node, rest: list):
        super().__init__([node], list(rest))


class BlockClusterStateProcessing(ServiceDisruptionScheme):
    """Drop cluster-state publish traffic TO one node — it keeps serving
    with a stale view (BlockClusterStateProcessing)."""

    PUBLISH_PREFIX = "internal:discovery/zen/publish"

    def __init__(self, blocked_node, publishers: list):
        super().__init__()
        self.blocked = blocked_node
        self.publishers = list(publishers)

    def _nodes(self) -> list:
        return self.publishers

    def _rule_for(self, node):
        target = _addr_of(self.blocked)

        def rule(addr, action):
            if addr == target and action.startswith(self.PUBLISH_PREFIX):
                return DROP
            return None
        return rule


class SlowClusterStateProcessing(BlockClusterStateProcessing):
    """Delay (not drop) state publishes to one node
    (SlowClusterStateProcessing)."""

    def __init__(self, slow_node, publishers: list, delay_s: float = 0.5):
        super().__init__(slow_node, publishers)
        self.delay_s = delay_s

    def _rule_for(self, node):
        target = _addr_of(self.blocked)

        def rule(addr, action):
            if addr == target and action.startswith(self.PUBLISH_PREFIX):
                return self.delay_s
            return None
        return rule


def wait_until(predicate, timeout: float = 10.0,
               interval: float = 0.05) -> bool:
    """Poll helper for disruption tests (assertBusy analog)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


# ---- message-granular fault schemes (service-seam, v2) ----------------------

#: cluster-critical traffic the flaky schemes leave alone by default —
#: randomly dropping fd pings / elections makes every scenario devolve
#: into "node removed", which the partition schemes already cover; the
#: flaky schemes exist to stress the RETRY paths of data/recovery RPCs
DATA_ACTION_PREFIXES = (
    "indices:data/",
    "internal:index/shard/recovery",
    "internal:snapshot/",
    "indices:admin/broadcast",
)


class FaultyTransport(ServiceDisruptionScheme):
    """Per-message seeded faults on the TransportService seam: each
    outbound message matching ``action_prefixes`` draws one verdict —
    drop / delay / duplicate / reorder — with the given probabilities
    (the remainder passes clean). Runs identically over LocalTransport
    and TcpTransport because the seam sits above the byte mover."""

    RULE_HOST = "service"

    def __init__(self, nodes: list, seed: int = 0,
                 drop: float = 0.0, delay: float = 0.0,
                 duplicate: float = 0.0, reorder: float = 0.0,
                 delay_range: tuple = (0.01, 0.15),
                 reorder_window: float = 0.05,
                 action_prefixes: tuple = DATA_ACTION_PREFIXES):
        super().__init__()
        self.nodes = list(nodes)
        self.seed = seed
        self.p_drop = drop
        self.p_delay = delay
        self.p_duplicate = duplicate
        self.p_reorder = reorder
        self.delay_range = delay_range
        self.reorder_window = reorder_window
        self.prefixes = tuple(action_prefixes or ())

    def _nodes(self) -> list:
        return self.nodes

    def _rule_for(self, node):
        # per-node rng derived from (seed, node name): deterministic
        # given the scheme seed, uncorrelated across nodes
        import zlib
        name = node.transport_service.local_node.name
        rng = random.Random(self.seed ^ zlib.crc32(name.encode()))

        def rule(addr, action):
            if self.prefixes and \
                    not any(action.startswith(p) for p in self.prefixes):
                return None
            r = rng.random()
            if r < self.p_drop:
                return DROP
            r -= self.p_drop
            if r < self.p_delay:
                return rng.uniform(*self.delay_range)
            r -= self.p_delay
            if r < self.p_duplicate:
                return (DUPLICATE, 1)
            r -= self.p_duplicate
            if r < self.p_reorder:
                return (REORDER, rng.uniform(0.0, self.reorder_window))
            return None
        return rule


class ActionDelay(ServiceDisruptionScheme):
    """Delay every message of the given action classes from the given
    nodes (service seam, so it works over TCP too) — the surgical tool
    for forcing a recovery/replication RPC to lose a race."""

    RULE_HOST = "service"

    def __init__(self, nodes: list, delay_s: float,
                 action_prefixes: tuple):
        super().__init__()
        self.nodes = list(nodes)
        self.delay_s = delay_s
        self.prefixes = tuple(action_prefixes)

    def _nodes(self) -> list:
        return self.nodes

    def _rule_for(self, node):
        def rule(addr, action):
            if any(action.startswith(p) for p in self.prefixes):
                return self.delay_s
            return None
        return rule


# ---- disk-fault scheme ------------------------------------------------------

class DiskFaultScheme:
    """Inject disk faults into a node's engines: translog appends/fsyncs
    raise OSError (or tear the frame mid-write with ``short_writes``) and
    store/commit writes raise OSError. Installed node-wide
    (IndicesService.disk_fault) so engines created while the fault is
    active inherit it — a bad disk doesn't heal because a shard was
    reallocated back. The engine must respond by self-failing the shard
    (Engine.fail_engine → shard-failed → master reallocates), never by
    wedging.

    ``ops`` filters which operations fault: any of "add", "sync"
    (translog) / "store.write", "store.commit" (engine flush)."""

    def __init__(self, node, ops: tuple = ("add", "sync", "store.write",
                                           "store.commit"),
                 index: str | None = None,
                 short_writes: bool = False, seed: int = 0):
        self.node = node
        self.ops = set(ops)
        self.index = index
        self.short_writes = short_writes
        self._rng = random.Random(seed)

    def _hook(self, op: str, data):
        if op not in self.ops:
            return None
        if op == "add" and self.short_writes and data:
            # torn frame: a strict prefix lands, then the append fails
            return data[:self._rng.randrange(1, len(data))]
        raise OSError(f"simulated disk fault [{op}]")

    def _engines(self):
        isvc = self.node.indices_service
        for name, svc in list(isvc.indices.items()):
            if self.index is not None and name != self.index:
                continue
            yield from list(svc.engines.values())

    def start_disrupting(self) -> None:
        if self.index is None:
            self.node.indices_service.disk_fault = self._hook
        for e in self._engines():
            e.disk_fault = self._hook
            e.translog.fault_hook = self._hook

    def stop_disrupting(self) -> None:
        if self.node.indices_service.disk_fault is self._hook:
            self.node.indices_service.disk_fault = None
        for e in self._engines():
            if e.disk_fault is self._hook:
                e.disk_fault = None
            if getattr(e.translog, "fault_hook", None) is self._hook:
                e.translog.fault_hook = None

    @contextlib.contextmanager
    def applied(self):
        self.start_disrupting()
        try:
            yield self
        finally:
            self.stop_disrupting()


# ---- brownout scheme (tail-tolerance chaos) ---------------------------------

class BrownoutScheme:
    """Sustained per-node SERVICE delay — browned out, not failed.

    Every shard search executing on an affected node is held
    ``delay_s`` seconds at a cooperative cancellation checkpoint before
    serving (the ``SearchActions.shard_query_delay`` seam). Distinct
    from :class:`NetworkDelaysPartition` in kind, not just degree: the
    delay sits INSIDE the serve path, on the search pool's threads, so
    it occupies pool capacity, shows up as queue depth in
    ``_cat/thread_pool`` / the piggybacked ARS signals, and is
    cancellable mid-hold (a hedged request's losing copy aborts at the
    checkpoint, releasing its breaker bytes) — a transit delay has none
    of those properties. Nothing is ever dropped: every request on a
    browned node eventually answers, correctly, just slowly. That is
    exactly the failure mode the tail-tolerance layer (ARS ranks,
    hedged requests, deadline-bounded partial results) exists for, and
    what plain next-copy-on-error failover cannot see."""

    def __init__(self, nodes: list, delay_s: float = 0.3,
                 seed: int = 0):
        self.nodes = list(nodes)
        self.delay_s = float(delay_s)
        self.seed = seed                   # replay-line provenance only
        self._saved: list[tuple] = []

    def start_disrupting(self) -> None:
        for n in self.nodes:
            self._saved.append((n, n.search_actions.shard_query_delay))
            n.search_actions.shard_query_delay = self.delay_s

    def stop_disrupting(self) -> None:
        for n, prev in reversed(self._saved):
            n.search_actions.shard_query_delay = prev
        self._saved.clear()

    @contextlib.contextmanager
    def applied(self):
        self.start_disrupting()
        try:
            yield self
        finally:
            self.stop_disrupting()


# ---- device-fault scheme (accelerator chaos) --------------------------------

#: the device touchpoints the DEFAULT chaos draw covers (jit_exec.
#: device_fault_point call sites): compiled per-segment/reader dispatch,
#: program compiles, host→device block uploads, device-side pack
#: composes, the collective-plane mesh dispatch, fused percolate lanes.
#: NOT here: ``reader-upload`` (the RPC fan-out's baseline reader
#: transfer, READER_UPLOAD_SITE) — the serving FLOOR every degraded
#: path falls back to; drawing it by default would leave chaos cases
#: with no working fallback, so targeted tests opt in via p_by_site
DEVICE_FAULT_SITES = ("dispatch", "compile", "upload", "compose",
                      "plane-dispatch", "percolate",
                      # impact-ordered lane touchpoints: quantized
                      # column/block-max upload, pack-level compose,
                      # and the block-max sweep dispatch
                      "impact-upload", "blockmax-compose",
                      "pruning-dispatch",
                      # dense/late-interaction lane touchpoints:
                      # vector block upload, fused MaxSim dispatch,
                      # and the in-program hybrid fusion dispatch
                      "vector-upload", "maxsim-dispatch",
                      "fusion-dispatch",
                      # the planner's fused impact→rescore dispatch
                      "rescore-dispatch",
                      # mesh-sharded retrieval lanes: placed block
                      # upload to owning devices, the pod-slice impact
                      # sweep dispatch, and the cross-chip knn merge
                      "block-placement-upload", "impact-shard-dispatch",
                      "knn-mesh-merge")
READER_UPLOAD_SITE = "reader-upload"


class DeviceFaultScheme:
    """Seeded accelerator-fault injection on jit_exec's device-fault
    seam: each device touchpoint draws from a replayable rng and, with
    probability ``p`` (overridable per site via ``p_by_site``), raises
    an accelerator-style error there — a plain
    :class:`jit_exec.DeviceFaultError` (dispatch/upload/compile
    failure), or with probability ``oom_fraction`` a
    :class:`jit_exec.DeviceOomError` (the RESOURCE_EXHAUSTED HBM-OOM
    shape, which triggers cold-block eviction before degrading).

    The seam is module-global (all in-process nodes share one device,
    exactly like deployment shares one device per process), so the
    scheme needs no node list. ``injected`` counts raises by site —
    the number the breaker/fallback counters must reconcile with.
    ``stop_disrupting`` restores the previous hook and (by default)
    resets the plane breaker so a tripped-open state cannot leak into
    unrelated tests.
    """

    def __init__(self, seed: int = 0, p: float = 0.0,
                 sites: tuple = DEVICE_FAULT_SITES,
                 p_by_site: dict | None = None,
                 oom_fraction: float = 0.0,
                 reset_breaker_on_stop: bool = True):
        self.seed = seed
        self.p = float(p)
        self.sites = tuple(sites)
        self.p_by_site = dict(p_by_site or {})
        self.oom_fraction = float(oom_fraction)
        self.reset_breaker_on_stop = reset_breaker_on_stop
        self._rng = random.Random(seed)
        self._prev = None
        self._active = False
        #: injected raises by site; ``calls`` counts every touchpoint
        #: reached (0 while the breaker gates device work entirely);
        #: ``calls_by_site`` splits that count so tests can assert on
        #: dispatch-class touchpoints alone (the open-breaker contract
        #: is ZERO DISPATCHES — floor uploads for the eager path are
        #: expected and harmless)
        self.injected: dict[str, int] = {}
        self.calls = 0
        self.calls_by_site: dict[str, int] = {}

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    def heal(self) -> None:
        """Stop injecting (the hook stays installed and keeps counting
        touchpoints) — the 'faults heal' half of a recovery scenario."""
        self.p = 0.0
        self.p_by_site = {}

    def dispatch_calls(self) -> int:
        """Touchpoints of the dispatch classes (dispatch /
        plane-dispatch / percolate) — the count the open-breaker
        zero-device-dispatch assertions reconcile against."""
        return sum(self.calls_by_site.get(s, 0)
                   for s in ("dispatch", "plane-dispatch", "percolate"))

    def _hook(self, site: str) -> None:
        from elasticsearch_tpu.search import jit_exec
        self.calls += 1
        self.calls_by_site[site] = self.calls_by_site.get(site, 0) + 1
        p = self.p_by_site.get(site, self.p if site in self.sites else 0.0)
        if p <= 0.0 or self._rng.random() >= p:
            return
        self.injected[site] = self.injected.get(site, 0) + 1
        if self.oom_fraction and self._rng.random() < self.oom_fraction:
            raise jit_exec.DeviceOomError(
                f"RESOURCE_EXHAUSTED: simulated HBM out of memory at "
                f"[{site}] (seed={self.seed})")
        raise jit_exec.DeviceFaultError(
            f"simulated device fault [{site}] (seed={self.seed})")

    def _chained(self, site: str) -> None:
        if self._prev is not None:
            self._prev(site)
        self._hook(site)

    def start_disrupting(self) -> None:
        if self._active:
            return
        from elasticsearch_tpu.search import jit_exec
        self._prev = jit_exec.set_device_fault_hook(self._chained)
        self._active = True

    def stop_disrupting(self) -> None:
        if not self._active:
            return
        from elasticsearch_tpu.search import jit_exec
        jit_exec.set_device_fault_hook(self._prev)
        self._prev = None
        self._active = False
        if self.reset_breaker_on_stop:
            jit_exec.plane_breaker.reset()

    @contextlib.contextmanager
    def applied(self):
        self.start_disrupting()
        try:
            yield self
        finally:
            self.stop_disrupting()


class StallScheme:
    """Seeded device-HANG injection on jit_exec's device-fault seam:
    the other half of the fault model. :class:`DeviceFaultScheme`
    raises — breakers and fallbacks see a typed error immediately; this
    scheme *holds*: with probability ``p`` (overridable per site via
    ``p_by_site``) a touchpoint simply blocks, the way a wedged XLA
    program, stuck H2D transfer or runaway compile behaves. Nothing
    raises at the seam, so only deadline-bounded waits and the dispatch
    watchdog make the hang observable.

    Two hold modes, drawn per injection from the replayable rng:

    * finite delay — hold for ``uniform(*delay_range)`` seconds (a slow
      wedge that eventually completes);
    * permanent wedge (``wedge_fraction`` of injections, or a
      ``delay_range`` of None) — hold until released.

    Every hold (finite or permanent) blocks on ONE shared release
    event, so :meth:`heal` / :meth:`stop_disrupting` release every held
    site immediately — the 'hang clears' half of a recovery scenario.
    Counters mirror DeviceFaultScheme: ``calls``/``calls_by_site``
    count touchpoints reached, ``injected`` counts holds by site,
    ``holding`` gauges threads currently held.
    """

    def __init__(self, seed: int = 0, p: float = 0.0,
                 sites: tuple = DEVICE_FAULT_SITES,
                 p_by_site: dict | None = None,
                 delay_range: tuple | None = (0.02, 0.12),
                 wedge_fraction: float = 0.0,
                 reset_breaker_on_stop: bool = True):
        self.seed = seed
        self.p = float(p)
        self.sites = tuple(sites)
        self.p_by_site = dict(p_by_site or {})
        self.delay_range = delay_range
        self.wedge_fraction = float(wedge_fraction)
        self.reset_breaker_on_stop = reset_breaker_on_stop
        self._rng = random.Random(seed)
        self._rng_lock = threading.Lock()
        self._release = threading.Event()
        self._prev = None
        self._active = False
        self.injected: dict[str, int] = {}
        self.calls = 0
        self.calls_by_site: dict[str, int] = {}
        #: threads currently held at the seam (gauge, not a counter)
        self.holding = 0

    @property
    def total_injected(self) -> int:
        return sum(self.injected.values())

    def heal(self) -> None:
        """Stop injecting AND release every held site (the hook stays
        installed and keeps counting touchpoints) — after heal() the
        device serves again and quarantine may probe-reopen."""
        self.p = 0.0
        self.p_by_site = {}
        self._release.set()

    def _hook(self, site: str) -> None:
        with self._rng_lock:
            self.calls += 1
            self.calls_by_site[site] = \
                self.calls_by_site.get(site, 0) + 1
            p = self.p_by_site.get(site,
                                   self.p if site in self.sites else 0.0)
            if p <= 0.0 or self._rng.random() >= p:
                return
            self.injected[site] = self.injected.get(site, 0) + 1
            wedge = self.delay_range is None or (
                self.wedge_fraction
                and self._rng.random() < self.wedge_fraction)
            dur = None if wedge else self._rng.uniform(*self.delay_range)
            self.holding += 1
        try:
            # cooperative hold: waits on the shared release event so
            # heal()/stop_disrupting() free every held thread at once;
            # a finite delay is the same wait with a timeout
            if dur is None:
                self._release.wait()
            else:
                self._release.wait(dur)
        finally:
            with self._rng_lock:
                self.holding -= 1

    def _chained(self, site: str) -> None:
        if self._prev is not None:
            self._prev(site)
        self._hook(site)

    def start_disrupting(self) -> None:
        if self._active:
            return
        from elasticsearch_tpu.search import jit_exec
        self._release.clear()
        self._prev = jit_exec.set_device_fault_hook(self._chained)
        self._active = True

    def stop_disrupting(self) -> None:
        if not self._active:
            return
        from elasticsearch_tpu.search import jit_exec
        self._release.set()             # free every held thread
        jit_exec.set_device_fault_hook(self._prev)
        self._prev = None
        self._active = False
        if self.reset_breaker_on_stop:
            jit_exec.plane_breaker.reset()

    @contextlib.contextmanager
    def applied(self):
        self.start_disrupting()
        try:
            yield self
        finally:
            self.stop_disrupting()


# ---- coordinator-kill scenario (task-management chaos) ----------------------

def run_coordinator_kill_case(seed: int, transport: str = "local") -> dict:
    """Seed-replayable coordinator-kill scenario (the task-management
    chaos scheme, v3): draw cluster/index/search shapes from ``seed``,
    start a fanned-out search whose shard tasks are HELD at a
    cancellation checkpoint on the data nodes, kill the coordinating
    node mid-search, and assert the survivors reap the orphaned child
    tasks — no task parented on the dead node remains, and request
    circuit-breaker bytes return to zero. Any assertion carries the seed
    so a failure replays exactly (the PR 1 matrix discipline).

    → summary dict {seed, nodes, shards, children_before_kill}."""
    import threading

    from elasticsearch_tpu.testing import InternalTestCluster

    rnd = random.Random(seed)
    num_nodes = rnd.randint(3, 4)
    shards = rnd.randint(2, 2 * (num_nodes - 1))
    ndocs = rnd.randint(8, 32)
    hold_s = rnd.uniform(4.0, 7.0)
    tag = f"[coordinator_kill seed={seed} transport={transport}]"
    cluster = InternalTestCluster(num_nodes=num_nodes, transport=transport)
    try:
        master = cluster.master()
        master.indices_service.create_index(
            "chaos_tasks", {"settings": {"number_of_shards": shards,
                                         "number_of_replicas": 0}})
        cluster.wait_for_health("green")
        for i in range(ndocs):
            master.index_doc("chaos_tasks", str(i),
                             {"body": f"doc {i} {rnd.random()}"})
        # a non-master coordinator: the master must survive the kill to
        # publish the node-left state that triggers the reap
        coordinator = rnd.choice(cluster.non_masters())
        for n in cluster.nodes:
            n.search_actions.shard_query_delay = hold_s

        def fire():
            try:
                coordinator.search("chaos_tasks",
                                   {"query": {"match_all": {}}})
            except Exception:       # noqa: BLE001 — dies with the kill
                pass
        searcher = threading.Thread(target=fire, daemon=True)
        searcher.start()
        survivors = [n for n in cluster.nodes if n is not coordinator]
        prefix = f"{coordinator.node_id}:"

        def children_on_survivors() -> int:
            return sum(
                1 for n in survivors
                for t in n.task_manager.list_tasks().values()
                if str(t.get("parent_task_id", "")).startswith(prefix))
        assert wait_until(lambda: children_on_survivors() > 0,
                          timeout=10.0), \
            f"{tag} no shard task ever reached a survivor node"
        children_before = children_on_survivors()
        kill_at = time.monotonic()
        cluster.stop_node(coordinator, graceful=False)     # the kill

        def reaped() -> bool:
            return children_on_survivors() == 0 and all(
                n.breaker_service.breaker("request").used == 0
                for n in survivors)
        assert wait_until(reaped, timeout=15.0), (
            f"{tag} orphaned tasks survived the reap pass: "
            f"{[(n.node_name, n.task_manager.list_tasks()) for n in survivors]}, "
            f"breakers={[(n.node_name, n.breaker_service.breaker('request').used) for n in survivors]}")
        return {"seed": seed, "nodes": num_nodes, "shards": shards,
                "children_before_kill": children_before,
                "reap_seconds": round(time.monotonic() - kill_at, 3)}
    finally:
        for n in list(cluster.nodes):
            n.search_actions.shard_query_delay = None
        cluster.close()


# ---- seeded scheme registry (the matrix draws from this) --------------------

#: names the randomized matrix can draw; each factory takes
#: (cluster_nodes, rnd) and returns a started-able scheme or None
SCHEME_NAMES = (
    "none",
    "partition_minority",
    "isolate_one",
    "delays",
    "flaky_drop",
    "flaky_delay",
    "duplicate",
    "reorder",
    "block_state_one",
    "slow_state_one",
    # accelerator faults (the device-fault seam; node list unused —
    # every in-process node shares the one device)
    "device_flaky",
    "device_oom",
    # device HANGS (the stall half of the fault model): finite holds at
    # the same seam — bounded waits + the dispatch watchdog must keep
    # every request inside its deadline
    "device_stall",
    # sustained per-node service delay (browned out, not failed) — the
    # tail-tolerance layer's target failure mode
    "brownout",
)


def build_scheme(name: str, nodes: list, rnd: random.Random):
    """Construct a disruption scheme by registry name over ``nodes``
    with all shape parameters drawn from ``rnd`` — the seeded entry
    point the randomized matrix (tests/test_randomized_matrix.py) and
    replay tooling share. → scheme or None ("none")."""
    seed = rnd.randrange(2 ** 31)
    if name == "device_flaky":
        # intermittent accelerator faults across every device touchpoint:
        # everything must degrade (fan-out / eager / rescue), never error
        return DeviceFaultScheme(seed=seed, p=rnd.uniform(0.05, 0.25))
    if name == "device_oom":
        # HBM-OOM shape: cold-block eviction then degrade
        return DeviceFaultScheme(seed=seed, p=rnd.uniform(0.05, 0.2),
                                 oom_fraction=1.0)
    if name == "device_stall":
        # finite holds only (the matrix must complete): slow-wedge
        # delays well under every deadline; the permanent-wedge mode
        # runs in the targeted stall scenarios/suite, which own the
        # heal/quarantine assertions
        return StallScheme(seed=seed, p=rnd.uniform(0.05, 0.2),
                           delay_range=(0.02, 0.1))
    if name == "brownout":
        # brown out ONE node's serve path: delay without drop. The delay
        # stays under the shard RPC timeout by orders of magnitude —
        # everything completes, just slowly (searches route around it
        # via ARS/hedging; writes are merely late)
        victim = nodes[rnd.randrange(len(nodes))]
        return BrownoutScheme([victim],
                              delay_s=rnd.uniform(0.1, 0.3), seed=seed)
    if name == "none" or len(nodes) < 2:
        return None
    if name == "partition_minority":
        n_min = rnd.randint(1, max((len(nodes) - 1) // 2, 1))
        minority = rnd.sample(nodes, n_min)
        majority = [n for n in nodes if n not in minority]
        return NetworkPartition(minority, majority)
    if name == "isolate_one":
        victim = nodes[rnd.randrange(len(nodes))]
        return IsolateNode(victim, [n for n in nodes if n is not victim])
    if name == "delays":
        # max_delay stays under half the test clusters' fd.ping_timeout
        # (0.3 s): the scheme should add latency, not fail fault
        # detection — spurious evictions belong to the partition/kill
        # schemes, which assert accordingly
        half = rnd.sample(nodes, max(len(nodes) // 2, 1))
        rest = [n for n in nodes if n not in half]
        return NetworkDelaysPartition(half, rest, min_delay=0.02,
                                      max_delay=0.1, seed=seed)
    if name == "flaky_drop":
        return FaultyTransport(nodes, seed=seed,
                               drop=rnd.uniform(0.02, 0.12))
    if name == "flaky_delay":
        return FaultyTransport(nodes, seed=seed,
                               delay=rnd.uniform(0.1, 0.4))
    if name == "duplicate":
        return FaultyTransport(nodes, seed=seed,
                               duplicate=rnd.uniform(0.05, 0.25))
    if name == "reorder":
        return FaultyTransport(nodes, seed=seed,
                               reorder=rnd.uniform(0.05, 0.3))
    if name == "block_state_one":
        blocked = nodes[rnd.randrange(len(nodes))]
        return BlockClusterStateProcessing(
            blocked, [n for n in nodes if n is not blocked])
    if name == "slow_state_one":
        slow = nodes[rnd.randrange(len(nodes))]
        return SlowClusterStateProcessing(
            slow, [n for n in nodes if n is not slow],
            delay_s=rnd.uniform(0.1, 0.4))
    raise ValueError(f"unknown scheme [{name}]")
