"""Model families — packaged retrieval pipelines.

The framework's "models" are retrieval programs over columnar indexes (the
way the reference's capability surface is BM25 lexical search, scripted
re-scoring, and script-based vector search — BASELINE.json configs):

* :class:`~elasticsearch_tpu.models.bm25.BM25Retriever` — lexical BM25
  (configs 1, 2, 5): batched multi-term scoring + top-k, single jitted
  program per (corpus bucket, T, k) shape.
* :class:`~elasticsearch_tpu.models.dense.DenseRetriever` — dense-vector
  brute-force cosine (config 4): one MXU matmul + top-k.
* :class:`~elasticsearch_tpu.models.hybrid.HybridRetriever` — weighted
  linear / RRF fusion of the two.
"""

from elasticsearch_tpu.models.bm25 import BM25Retriever, PackedTextIndex
from elasticsearch_tpu.models.dense import DenseRetriever
from elasticsearch_tpu.models.hybrid import HybridRetriever

__all__ = ["BM25Retriever", "PackedTextIndex", "DenseRetriever",
           "HybridRetriever"]
