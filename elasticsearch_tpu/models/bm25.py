"""BM25Retriever — the flagship lexical scoring pipeline.

The standalone, benchable form of the engine's match-query path
(BASELINE.json configs 1/2/5): a packed text index (forward impact layout,
index/segment.py) + one jitted XLA program computing batched BM25 scores and
top-k. ``__graft_entry__.entry()`` exposes exactly this program.

Reference path being replaced: QueryPhase's collector loop over Lucene
TermScorers (core/search/query/QueryPhase.java:314) and the per-shard
fan-out/merge (SearchPhaseController.java:165) — here one device program
scores Q queries against N docs with zero host round-trips.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.analysis.analyzers import Analyzer, BUILTIN_ANALYZERS
from elasticsearch_tpu.ops import lexical, topk as topk_ops
from elasticsearch_tpu.ops.similarity import BM25Params, idf as bm25_idf


@dataclass
class PackedTextIndex:
    """One field's forward impact index in packed (device-ready) form."""
    terms: dict[str, int]            # term → id
    uterms: np.ndarray               # [Np, U] int32
    utf: np.ndarray                  # [Np, U] float32
    doc_len: np.ndarray              # [Np] int32
    live: np.ndarray                 # [Np] bool
    df: np.ndarray                   # [V] int32
    num_docs: int
    total_tokens: int

    @property
    def avgdl(self) -> float:
        return self.total_tokens / max(self.num_docs, 1)

    @staticmethod
    def from_texts(texts: list[str], analyzer: Analyzer | None = None,
                   pad_docs: int | None = None,
                   max_unique: int | None = None) -> "PackedTextIndex":
        analyzer = analyzer or BUILTIN_ANALYZERS["english"]
        vocab: dict[str, int] = {}
        doc_counts = []
        doc_lens = []
        for text in texts:
            counts: dict[int, int] = {}
            toks = analyzer.terms(text)
            for t in toks:
                tid = vocab.setdefault(t, len(vocab))
                counts[tid] = counts.get(tid, 0) + 1
            doc_counts.append(counts)
            doc_lens.append(len(toks))
        n = len(texts)
        np_docs = pad_docs or n
        u = max_unique or max((len(c) for c in doc_counts), default=1)
        uterms = np.full((np_docs, u), -1, np.int32)
        utf = np.zeros((np_docs, u), np.float32)
        df = np.zeros(max(len(vocab), 1), np.int32)
        for i, counts in enumerate(doc_counts):
            for j, (tid, tf) in enumerate(sorted(counts.items())[:u]):
                uterms[i, j] = tid
                utf[i, j] = tf
                df[tid] += 1
        doc_len = np.zeros(np_docs, np.int32)
        doc_len[:n] = doc_lens
        live = np.zeros(np_docs, bool)
        live[:n] = True
        return PackedTextIndex(terms=vocab, uterms=uterms, utf=utf,
                               doc_len=doc_len, live=live, df=df, num_docs=n,
                               total_tokens=int(sum(doc_lens)))


@partial(jax.jit, static_argnames=("k", "k1", "b"))
def bm25_topk_batch(uterms, utf, doc_len, live, qtids, qidf, avgdl,
                    k: int, k1: float = 1.2, b: float = 0.75):
    """The flagship forward program: Q queries → top-k (scores, doc ids).

    uterms/utf: [N, U]; doc_len/live: [N]; qtids/qidf: [Q, T]; avgdl scalar.
    Returns (top_scores [Q, k], top_docs [Q, k]).
    """
    def one(qt, qi):
        scores, _ = lexical.bm25_match(
            uterms, utf, doc_len, qt, qi,
            jnp.ones(qt.shape[0], jnp.float32), k1, b, avgdl)
        return topk_ops.top_k(scores, live & (scores > 0), k)
    return jax.vmap(one)(qtids, qidf)


class BM25Retriever:
    def __init__(self, index: PackedTextIndex,
                 analyzer: Analyzer | None = None,
                 params: BM25Params = BM25Params(), device=None):
        self.index = index
        self.analyzer = analyzer or BUILTIN_ANALYZERS["english"]
        self.params = params
        from elasticsearch_tpu.search.jit_exec import seam_device_put
        put = lambda x: seam_device_put(x, device)    # noqa: E731
        self.d_uterms = put(index.uterms)
        self.d_utf = put(index.utf)
        self.d_doc_len = put(index.doc_len)
        self.d_live = put(index.live)

    def encode_queries(self, queries: list[str], pad_terms: int | None = None):
        """Analyze + resolve term ids and idf → packed [Q, T] arrays."""
        per_q = [self.analyzer.terms(q) for q in queries]
        t = pad_terms or max((len(x) for x in per_q), default=1)
        qtids = np.full((len(queries), t), -1, np.int32)
        qidf = np.zeros((len(queries), t), np.float32)
        n = self.index.num_docs
        for i, terms in enumerate(per_q):
            for j, term in enumerate(terms[:t]):
                tid = self.index.terms.get(term, -1)
                qtids[i, j] = tid
                if tid >= 0:
                    qidf[i, j] = bm25_idf(float(self.index.df[tid]), n)
        return qtids, qidf

    def search(self, queries: list[str], k: int = 10):
        qtids, qidf = self.encode_queries(queries)
        scores, docs = bm25_topk_batch(
            self.d_uterms, self.d_utf, self.d_doc_len, self.d_live,
            jnp.asarray(qtids), jnp.asarray(qidf),
            np.float32(self.index.avgdl), k,
            self.params.k1, self.params.b)
        return np.asarray(scores), np.asarray(docs)

    def search_packed(self, qtids, qidf, k: int = 10):
        """Pre-encoded query path (bench hot loop — no host analysis)."""
        return bm25_topk_batch(
            self.d_uterms, self.d_utf, self.d_doc_len, self.d_live,
            qtids, qidf, np.float32(self.index.avgdl), k,
            self.params.k1, self.params.b)
