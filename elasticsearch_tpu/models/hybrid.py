"""HybridRetriever — lexical + dense fusion.

No reference-era equivalent (hybrid arrived later as RRF); included because
a complete retrieval framework needs it and both legs already run on-device.
Fusion modes: ``rrf`` (reciprocal rank fusion, k=60 default) and ``linear``
(weighted score sum over min-max-normalized legs).
"""

from __future__ import annotations

import numpy as np

from elasticsearch_tpu.models.bm25 import BM25Retriever
from elasticsearch_tpu.models.dense import DenseRetriever


class HybridRetriever:
    def __init__(self, lexical: BM25Retriever, dense: DenseRetriever,
                 mode: str = "rrf", rrf_k: int = 60,
                 lexical_weight: float = 0.5):
        self.lexical = lexical
        self.dense = dense
        self.mode = mode
        self.rrf_k = rrf_k
        self.lexical_weight = lexical_weight

    def search(self, queries: list[str], query_vectors: np.ndarray,
               k: int = 10, depth: int = 100):
        ls, ld = self.lexical.search(queries, k=depth)
        ds, dd = self.dense.search(query_vectors, k=depth)
        out_scores = np.zeros((len(queries), k), np.float32)
        out_docs = np.full((len(queries), k), -1, np.int64)
        for qi in range(len(queries)):
            fused: dict[int, float] = {}
            if self.mode == "rrf":
                for rank, doc in enumerate(ld[qi]):
                    if doc >= 0:
                        fused[doc] = fused.get(doc, 0.0) + \
                            1.0 / (self.rrf_k + rank + 1)
                for rank, doc in enumerate(dd[qi]):
                    if doc >= 0:
                        fused[doc] = fused.get(doc, 0.0) + \
                            1.0 / (self.rrf_k + rank + 1)
            else:  # linear with min-max normalization per leg
                def norm(scores, docs):
                    valid = docs >= 0
                    if not valid.any():
                        return {}
                    s = scores[valid]
                    lo, hi = float(s.min()), float(s.max())
                    rng = (hi - lo) or 1.0
                    return {int(d): (float(x) - lo) / rng
                            for d, x in zip(docs[valid], s)}
                for d, s in norm(ls[qi], ld[qi]).items():
                    fused[d] = fused.get(d, 0.0) + self.lexical_weight * s
                for d, s in norm(ds[qi], dd[qi]).items():
                    fused[d] = fused.get(d, 0.0) + (1 - self.lexical_weight) * s
            ranked = sorted(fused.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
            for j, (doc, score) in enumerate(ranked):
                out_docs[qi, j] = doc
                out_scores[qi, j] = score
        return out_scores, out_docs
