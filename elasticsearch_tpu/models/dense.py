"""DenseRetriever — brute-force exact cosine kNN (BASELINE config 4).

Reference-equivalent: script_score cosine over binary doc values
(core/common/lucene/search/function/ScriptScoreFunction.java), which is a
per-doc interpreted loop on the JVM. Here the whole batch is one
[Q, D] × [D, N] MXU matmul + top-k.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from elasticsearch_tpu.ops import topk as topk_ops
from elasticsearch_tpu.ops.vector import l2_normalize


@partial(jax.jit, static_argnames=("k", "use_bf16"))
def cosine_topk_batch(vecs, live, qs, k: int, use_bf16: bool = False):
    """vecs: [N, D] row-normalized; qs: [Q, D] → (scores [Q,k], docs [Q,k])."""
    qn = l2_normalize(qs, axis=-1)
    if use_bf16:
        scores = (qn.astype(jnp.bfloat16) @ vecs.astype(jnp.bfloat16).T
                  ).astype(jnp.float32)
    else:
        scores = qn @ vecs.T
    def one(s):
        return topk_ops.top_k(s, live, k)
    return jax.vmap(one)(scores)


class DenseRetriever:
    def __init__(self, vectors: np.ndarray, num_docs: int | None = None,
                 device=None, use_bf16: bool = False):
        n = num_docs if num_docs is not None else vectors.shape[0]
        norms = np.linalg.norm(vectors, axis=1, keepdims=True)
        normed = (vectors / np.maximum(norms, 1e-12)).astype(np.float32)
        live = np.zeros(vectors.shape[0], bool)
        live[:n] = True
        from elasticsearch_tpu.search.jit_exec import seam_device_put
        put = lambda x: seam_device_put(x, device)    # noqa: E731
        self.d_vecs = put(normed)
        self.d_live = put(live)
        self.use_bf16 = use_bf16
        self.num_docs = n
        self.dims = vectors.shape[1]

    def search(self, queries: np.ndarray, k: int = 10):
        scores, docs = cosine_topk_batch(self.d_vecs, self.d_live,
                                         jnp.asarray(queries, jnp.float32),
                                         k, self.use_bf16)
        return np.asarray(scores), np.asarray(docs)
