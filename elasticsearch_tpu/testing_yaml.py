"""Runner for the reference's REST YAML conformance suites.

The reference ships an implementation-independent acceptance suite
(rest-api-spec/src/main/resources/rest-api-spec/test, 84 dirs) executed by
ESRestTestCase (test/test/rest/): every test is a sequence of `do` steps
(API calls, resolved through the machine-readable api specs in
rest-api-spec/api/*.json) and assertions (match/length/is_true/...). This
runner executes those YAML files against OUR RestController in-process —
the cheapest possible cross-implementation contract check.

Deliberate compatibility shims, applied on the COMPARISON side only (the
server keeps its modern response shapes):
* ``hits.total`` — this framework answers the modern ``{"value", "relation"}``
  object; 2.x suites expect the bare count, so a {"value": N} object
  compares equal to N.
* stringified YAML bodies (``body: "{ _source: true }"``) parse as YAML,
  exactly like the reference runner.

Tests demanding unsupported harness features (`skip: features:`) or
versions outside ours are reported as skipped, like ESRestTestCase.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from pathlib import Path

import yaml


@dataclass
class StepFailure(Exception):
    step: str
    reason: str

    def __str__(self):
        return f"[{self.step}] {self.reason}"


@dataclass
class TestResult:
    suite: str
    name: str
    status: str                 # passed | failed | skipped
    reason: str = ""


@dataclass
class ApiSpec:
    name: str
    methods: list
    paths: list
    parts: set
    params: set
    body: bool


# our fictional 2.x-line version for `skip: version:` ranges
RUNNER_VERSION = (2, 1, 0)
SUPPORTED_FEATURES: set[str] = set()


def _parse_version(s: str):
    nums = [int(x) for x in re.findall(r"\d+", s)[:3]]
    while len(nums) < 3:
        nums.append(0)
    return tuple(nums)


def _version_skipped(spec: str) -> bool:
    spec = str(spec).strip()
    if spec == "all":
        return True
    m = re.match(r"^(.*?)\s*-\s*(.*)$", spec)
    if not m:
        return False
    lo = _parse_version(m.group(1)) if m.group(1).strip() else (0, 0, 0)
    hi = _parse_version(m.group(2)) if m.group(2).strip() else (99, 0, 0)
    return lo <= RUNNER_VERSION <= hi


class YamlRestRunner:
    def __init__(self, spec_dir: Path):
        """spec_dir: .../rest-api-spec (containing api/ and test/)."""
        self.spec_dir = Path(spec_dir)
        self.apis: dict[str, ApiSpec] = {}
        for f in (self.spec_dir / "api").glob("*.json"):
            doc = json.loads(f.read_text())
            ((name, spec),) = doc.items()
            url = spec.get("url", {})
            self.apis[name] = ApiSpec(
                name=name,
                methods=spec.get("methods", ["GET"]),
                paths=url.get("paths", [url.get("path", "/")]),
                parts=set(url.get("parts", {})),
                params=set(url.get("params", {})),
                body=spec.get("body") is not None)

    # ------------------------------------------------------------------ node

    def _fresh_controller(self, node):
        from elasticsearch_tpu.rest.controller import RestController
        from elasticsearch_tpu.rest.handlers import register_all
        controller = RestController()
        register_all(controller, node)
        return controller

    def _wipe(self, node) -> None:
        """Between-tests cleanup (ESRestTestCase wipes indices/templates).
        Iterates cluster-state indices, not local services — closed indices
        have no local IndexService but must be wiped too."""
        for name in list(node.cluster_service.state().indices):
            try:
                node.indices_service.delete_index(name)
            except Exception:               # noqa: BLE001 — best effort
                pass
        st = node.cluster_service.state()
        for tpl in list(getattr(st, "templates", {}) or {}):
            try:
                node.delete_template(tpl)
            except Exception:               # noqa: BLE001 — best effort
                pass

    # ----------------------------------------------------------------- suite

    def run_suite(self, suite_path: Path, node) -> list[TestResult]:
        rel = str(suite_path.relative_to(self.spec_dir / "test"))
        try:
            docs = list(yaml.safe_load_all(suite_path.read_text()))
        except yaml.YAMLError as e:
            return [TestResult(rel, "<parse>", "failed", f"yaml: {e}")]
        setup_steps: list = []
        tests: list[tuple[str, list]] = []
        for doc in docs:
            if not isinstance(doc, dict):
                continue
            for name, steps in doc.items():
                if name == "setup":
                    setup_steps = steps or []
                else:
                    tests.append((name, steps or []))
        results = []
        controller = self._fresh_controller(node)
        for name, steps in tests:
            self._wipe(node)
            try:
                ctx = _Ctx(controller=controller, runner=self)
                for si, step in enumerate(setup_steps):
                    try:
                        ctx.run_step(step)
                    except StepFailure as e:
                        raise StepFailure(f"setup[{si}]:{e.step}", e.reason)
                for si, step in enumerate(steps):
                    try:
                        ctx.run_step(step)
                    except StepFailure as e:
                        raise StepFailure(f"step[{si}]:{e.step}", e.reason)
                results.append(TestResult(rel, name, "passed"))
            except _Skipped as e:
                results.append(TestResult(rel, name, "skipped", str(e)))
            except StepFailure as e:
                results.append(TestResult(rel, name, "failed", str(e)))
            except Exception as e:          # noqa: BLE001 — suite robustness
                results.append(TestResult(rel, name, "failed",
                                          f"{type(e).__name__}: {e}"))
        return results

    # ------------------------------------------------------------------- api

    def call(self, controller, api: str, args: dict):
        args = dict(args or {})
        if api == "create" and "create" not in self.apis:
            # the 2.x spec has no create.json; the reference runner maps it
            # onto index with op_type=create
            api = "index"
            args["op_type"] = "create"
        spec = self.apis.get(api)
        if spec is None:
            raise StepFailure("do", f"unknown api [{api}]")
        body = args.pop("body", None)
        parts = {k: v for k, v in args.items()
                 if k in spec.parts and v not in ("", [], None)}
        query = {k: v for k, v in args.items() if k not in spec.parts}
        # choose the most specific path whose parts are all provided
        best = None
        for path in spec.paths:
            needed = set(re.findall(r"{(\w+)}", path))
            if needed <= set(parts):
                if best is None or len(needed) > len(best[1]):
                    best = (path, needed)
        if best is None:
            raise StepFailure("do", f"[{api}] missing url parts for "
                                    f"{spec.paths}: have {sorted(parts)}")
        path, needed = best
        for k in needed:
            v = parts[k]
            if isinstance(v, list):
                v = ",".join(str(x) for x in v)
            path = path.replace(f"{{{k}}}", str(v))
        if query:
            from urllib.parse import urlencode
            path += "?" + urlencode({k: _qval(v) for k, v in query.items()})
        if body is None:
            raw = b""
        elif isinstance(body, (dict,)):
            raw = json.dumps(body).encode()
        elif isinstance(body, list):        # bulk-style NDJSON
            raw = ("\n".join(
                x if isinstance(x, str) else json.dumps(x)
                for x in body) + "\n").encode()
        else:                               # stringified YAML body
            text = str(body)
            try:
                parsed = yaml.safe_load(text)
            except yaml.YAMLError:
                # a raw NDJSON blob (multiple JSON docs) — pass through
                parsed = None
            if parsed is None:
                raw = text.encode() if text.endswith("\n") \
                    else (text + "\n").encode()
            elif isinstance(parsed, list):
                raw = ("\n".join(json.dumps(x) for x in parsed)
                       + "\n").encode()
            else:
                raw = json.dumps(parsed).encode()
        method = "POST" if (raw and "POST" in spec.methods) \
            else spec.methods[0]
        status, resp = controller.dispatch(method, path, raw)
        if spec.methods == ["HEAD"]:
            # exists-style APIs answer a boolean (the reference runner
            # translates HEAD 200/404 to true/false); other statuses are
            # real errors and must stay visible to catch: steps
            if status not in (200, 404):
                return status, resp
            return 200, status == 200
        return status, resp


def _qval(v):
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, list):
        return ",".join(str(x) for x in v)
    return v


class _Skipped(Exception):
    pass


_CATCH_STATUS = {"missing": (404,), "conflict": (409,),
                 "bad_request": (400,), "param": (400,),
                 "forbidden": (403,), "unavailable": (503,),
                 "request_timeout": (408,)}


@dataclass
class _Ctx:
    controller: object
    runner: YamlRestRunner
    stash: dict = field(default_factory=dict)
    response: object = None

    # -------------------------------------------------------------- steps

    def run_step(self, step: dict) -> None:
        ((kind, payload),) = step.items()
        fn = getattr(self, f"_s_{kind}", None)
        if fn is None:
            raise StepFailure(kind, "unsupported step type")
        fn(payload)

    def _s_skip(self, spec: dict) -> None:
        feats = spec.get("features") or []
        if isinstance(feats, str):
            feats = [feats]
        missing = [f for f in feats if f not in SUPPORTED_FEATURES]
        if missing:
            raise _Skipped(f"features {missing}")
        if "version" in spec and _version_skipped(spec["version"]):
            raise _Skipped(f"version {spec['version']}: "
                           f"{spec.get('reason', '')}")

    def _s_do(self, spec: dict) -> None:
        spec = dict(spec)
        catch = spec.pop("catch", None)
        spec.pop("warnings", None)
        spec.pop("headers", None)
        ((api, args),) = spec.items()
        args = dict(self._sub(args) or {})
        ignore = args.pop("ignore", None)
        ignored = set()
        if ignore is not None:
            ignored = {int(x) for x in
                       (ignore if isinstance(ignore, list) else [ignore])}
        try:
            status, resp = self.runner.call(self.controller, api, args)
        except StepFailure as e:
            if catch == "param" and "missing url parts" in e.reason:
                # client-side validation error — exactly what catch:param
                # asserts (the reference runner's ValidationException)
                return
            raise
        self.response = resp
        if catch is not None:
            if status < 400:
                raise StepFailure("do", f"[{api}] expected error [{catch}], "
                                        f"got {status}")
            expected = _CATCH_STATUS.get(catch)
            if expected is not None and status not in expected:
                raise StepFailure("do", f"[{api}] expected {catch} "
                                        f"{expected}, got {status}: {resp}")
            return
        if status >= 400 and status not in ignored:
            raise StepFailure("do", f"[{api}] failed {status}: "
                                    f"{json.dumps(resp)[:300]}")

    def _s_set(self, spec: dict) -> None:
        for path, var in spec.items():
            self.stash[var] = self._lookup(path)

    def _s_match(self, spec: dict) -> None:
        for path, want in spec.items():
            got = self._lookup(path)
            want = self._sub(want)
            if isinstance(want, str) and len(want) > 1 and \
                    want.startswith("/") and want.rstrip().endswith("/"):
                pattern = want.strip().strip("/")
                if re.search(pattern, str(got), re.VERBOSE) is None:
                    raise StepFailure(
                        "match", f"{path}: /{pattern}/ !~ {got!r}")
                continue
            if not _eq(got, want):
                raise StepFailure("match", f"{path}: got {got!r}, "
                                           f"want {want!r}")

    def _s_length(self, spec: dict) -> None:
        for path, want in spec.items():
            got = self._lookup(path)
            n = len(got) if got is not None else 0
            if n != int(self._sub(want)):
                raise StepFailure("length", f"{path}: len {n} != {want}")

    @staticmethod
    def _falsy(got) -> bool:
        """Reference Is{True,False}Assertion semantics: null, "", "false"
        (ignoring case), and "0" are false — note [] and {} stringify to
        "[]"/"{}" and therefore count as TRUE, unlike Python truthiness."""
        if got is None:
            return True
        s = "false" if got is False else "true" if got is True else str(got)
        return s in ("", "0") or s.lower() == "false"

    def _s_is_true(self, path) -> None:
        got = self._lookup(path)
        if self._falsy(got):
            raise StepFailure("is_true", f"{path}: {got!r}")

    def _s_is_false(self, path) -> None:
        got = self._lookup(path)
        if not self._falsy(got):
            raise StepFailure("is_false", f"{path}: {got!r}")

    def _cmp(self, spec, op, name):
        for path, want in spec.items():
            got = _total_value(self._lookup(path))
            want = _total_value(self._sub(want))
            if not op(float(got), float(want)):
                raise StepFailure(name, f"{path}: {got!r} vs {want!r}")

    def _s_gt(self, spec):
        self._cmp(spec, lambda a, b: a > b, "gt")

    def _s_gte(self, spec):
        self._cmp(spec, lambda a, b: a >= b, "gte")

    def _s_lt(self, spec):
        self._cmp(spec, lambda a, b: a < b, "lt")

    def _s_lte(self, spec):
        self._cmp(spec, lambda a, b: a <= b, "lte")

    # -------------------------------------------------------------- lookup

    def _lookup(self, path):
        if path in ("$body", ""):
            return self.response
        node = self.response
        for part in _split_path(str(path)):
            part = self.stash.get(part[1:], part) if part.startswith("$") \
                else part
            if isinstance(node, dict):
                if part in node:
                    node = node[part]
                    continue
                return None
            if isinstance(node, list):
                try:
                    node = node[int(part)]
                    continue
                except (ValueError, IndexError):
                    return None
            return None
        return _total_value(node)

    def _sub(self, obj):
        """$stash substitution through params/bodies/expectations."""
        if isinstance(obj, str):
            if obj.startswith("$"):
                return self.stash.get(obj[1:], obj)
            return obj
        if isinstance(obj, dict):
            return {self._sub(k) if isinstance(k, str) else k: self._sub(v)
                    for k, v in obj.items()}
        if isinstance(obj, list):
            return [self._sub(v) for v in obj]
        return obj


def _split_path(path: str) -> list[str]:
    out, cur, esc = [], "", False
    for ch in path:
        if esc:
            cur += ch
            esc = False
        elif ch == "\\":
            esc = True
        elif ch == ".":
            out.append(cur)
            cur = ""
        else:
            cur += ch
    out.append(cur)
    return [p for p in out if p != ""]


def _total_value(v):
    """Modern {"value": N, "relation": ...} totals compare as bare counts
    (the 2.x suites predate the object form)."""
    if isinstance(v, dict) and "value" in v and \
            set(v) <= {"value", "relation"}:
        return v["value"]
    return v


def _eq(got, want) -> bool:
    got, want = _total_value(got), _total_value(want)
    if isinstance(want, float) or isinstance(got, float):
        try:
            return abs(float(got) - float(want)) <= 1e-6 * max(
                1.0, abs(float(want)))
        except (TypeError, ValueError):
            return False
    if isinstance(want, bool) or isinstance(got, bool):
        return bool(got) == bool(want)
    if isinstance(want, int) and isinstance(got, int):
        return got == want
    if isinstance(want, dict) and isinstance(got, dict):
        return all(k in got and _eq(got[k], v) for k, v in want.items()) \
            and set(got) == set(want)
    return got == want
