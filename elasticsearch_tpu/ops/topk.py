"""Top-k selection and cross-shard/segment merge.

Lucene's TopScoreDocCollector heap (core/search/query/QueryPhase.java:196)
becomes ``lax.top_k``; the coordinator's cross-shard merge
(SearchPhaseController.sortDocs via TopDocs.merge,
core/search/controller/SearchPhaseController.java:165-268) becomes a
concat + re-top-k that stays on device — inside shard_map it runs after an
all_gather over the shard mesh axis so the whole scatter-gather-reduce is
one XLA program over ICI.

Tie-breaking matches Lucene exactly because ``lax.top_k`` is stable (equal
values → lower index first): within a segment, index order == doc id order;
across shards, concatenating in shard order before re-top-k reproduces
TopDocs.merge's (shard index, position) tie-break.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)


def top_k(scores, mask, k: int, doc_base: int = 0):
    """Per-segment/shard top-k.

    Args:
      scores: [N] f32; mask: [N] bool (padding/deleted/filtered-out rows False)
      k: static int; doc_base: global doc id of row 0 (segment/shard offset)

    Returns (top_scores[k] f32, top_docs[k] int32 global ids); empty slots
    have score -inf and doc id -1.
    """
    masked = jnp.where(mask, scores, NEG_INF)
    kk = min(k, masked.shape[-1])
    top_scores, idx = jax.lax.top_k(masked, kk)
    valid = top_scores > NEG_INF
    top_docs = jnp.where(valid, idx.astype(jnp.int32) + doc_base, -1)
    top_scores = jnp.where(valid, top_scores, NEG_INF)
    if kk < k:   # corpus smaller than k: pad to the requested static width
        top_scores = jnp.pad(top_scores, (0, k - kk), constant_values=NEG_INF)
        top_docs = jnp.pad(top_docs, (0, k - kk), constant_values=-1)
    return top_scores, top_docs


def merge_top_k(scores_list, docs_list, k: int):
    """Merge several (scores[k_i], docs[k_i]) rankings → global top-k.

    Inputs must be concatenated in shard/segment order; stability of top_k
    then reproduces the reference's merge tie-breaking.
    """
    scores = jnp.concatenate(scores_list)
    docs = jnp.concatenate(docs_list)
    masked = jnp.where(docs >= 0, scores, NEG_INF)
    top_scores, idx = jax.lax.top_k(masked, min(k, scores.shape[0]))
    valid = top_scores > NEG_INF
    return (jnp.where(valid, top_scores, NEG_INF),
            jnp.where(valid, docs[idx], -1))


def merge_top_k_batch(scores_list, docs_list, k: int, bases):
    """Batched cross-segment merge: per-segment ``([B, k_s], [B, k_s])``
    rankings (segment-LOCAL doc ids) → global ``([B, k], [B, k])``.

    The batch-axis companion of :func:`merge_top_k` for the vmapped query
    path (jit_exec.run_segment_batch): `bases` maps each segment's local
    ids to reader-global ids inside the program, and concatenation in
    segment order + stable top_k keeps the reference's merge tie-break
    (TopDocs.merge, core/search/controller/SearchPhaseController.java:165).
    """
    return _merge_top_k_batch(tuple(scores_list), tuple(docs_list), k,
                              tuple(int(b) for b in bases))


def merge_top_k_batch_body(scores_list, docs_list, k: int, bases):
    """Traceable body shared by the standalone jitted entry below and the
    fused reader program (jit_exec.run_reader_batch) — ONE copy of the
    tie-break / -inf-pad contract."""
    docs = jnp.concatenate(
        [jnp.where(d >= 0, d + b, -1) for d, b in zip(docs_list, bases)],
        axis=1)
    scores = jnp.concatenate(scores_list, axis=1)
    masked = jnp.where(docs >= 0, scores, NEG_INF)
    kk = min(k, masked.shape[1])
    top_scores, idx = jax.lax.top_k(masked, kk)
    valid = top_scores > NEG_INF
    top_docs = jnp.where(valid, jnp.take_along_axis(docs, idx, axis=1), -1)
    top_scores = jnp.where(valid, top_scores, NEG_INF)
    if kk < k:
        top_scores = jnp.pad(top_scores, ((0, 0), (0, k - kk)),
                             constant_values=NEG_INF)
        top_docs = jnp.pad(top_docs, ((0, 0), (0, k - kk)),
                           constant_values=-1)
    return top_scores, top_docs


_merge_top_k_batch = partial(jax.jit, static_argnames=("k", "bases"))(
    merge_top_k_batch_body)


def pack_batch_result(top_scores, top_docs, counts):
    """Pack a batched merge result into ONE f32 array ``[B, 2k+1]``
    (scores ‖ doc-ids ‖ count) so the host needs a single device→host
    fetch per batch — round-trip latency, not bandwidth, dominates fetch
    cost on a tunneled interconnect. Doc ids and counts are exact in f32
    below 2**24; callers must use the unpacked path beyond that."""
    return _pack_batch_result(top_scores, top_docs, counts)


def pack_batch_result_body(top_scores, top_docs, counts):
    """Traceable body (shared with the fused reader program)."""
    return jnp.concatenate(
        [top_scores, top_docs.astype(jnp.float32),
         counts.astype(jnp.float32)[:, None]], axis=1)


_pack_batch_result = jax.jit(pack_batch_result_body)


def unpack_batch_result(packed: "np.ndarray", k: int):
    """Host-side inverse of :func:`pack_batch_result` →
    (scores [B,k] f32, docs [B,k] i32, counts [B] i64)."""
    import numpy as np
    scores = packed[:, :k]
    docs = packed[:, k:2 * k].astype(np.int32)
    counts = packed[:, 2 * k].astype(np.int64)
    return scores, docs, counts


def count_matches(mask):
    """Total hits (the search response's hits.total)."""
    return mask.sum(dtype=jnp.int32)


def max_score(scores, mask):
    return jnp.max(jnp.where(mask, scores, NEG_INF))
