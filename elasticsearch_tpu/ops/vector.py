"""Dense-vector scoring — brute-force exact kNN on the MXU.

f32 is the default (bf16 input rounding visibly reorders near-tie cosine
rankings — recall parity first); pass use_bf16=True to trade exactness for
~2x MXU throughput when the corpus tolerates it.

The reference era has no dense_vector type; its equivalent is binary doc
values + script cosine (BASELINE.md config 4,
core/common/lucene/search/function/ScriptScoreFunction.java). Here vectors
are first-class [N, D] matrices: batched cosine/dot scoring is a single
bf16 matmul — exactly what the 128×128 systolic array is built for.
"""

from __future__ import annotations

import jax.numpy as jnp


def l2_normalize(x, axis=-1, eps=1e-12):
    return x / jnp.sqrt((x * x).sum(axis=axis, keepdims=True) + eps)


def cosine_scores(vecs, exists, q, use_bf16: bool = False):
    """Cosine similarity of one query vector against all docs.

    vecs: [N, D] f32 (pre-normalized at reader build); q: [D] f32.
    Returns scores[N] f32 in [-1, 1]; non-existent rows score 0.
    """
    qn = l2_normalize(q)
    if use_bf16:
        s = (vecs.astype(jnp.bfloat16) @ qn.astype(jnp.bfloat16)).astype(jnp.float32)
    else:
        s = vecs @ qn
    return jnp.where(exists, s, 0.0)


def cosine_scores_batch(vecs, exists, qs, use_bf16: bool = False):
    """qs: [Q, D] → scores [Q, N]. One MXU matmul for the whole batch."""
    qn = l2_normalize(qs, axis=-1)
    if use_bf16:
        s = (qn.astype(jnp.bfloat16) @ vecs.astype(jnp.bfloat16).T).astype(jnp.float32)
    else:
        s = qn @ vecs.T
    return jnp.where(exists[None, :], s, 0.0)


def dot_scores(vecs, exists, q):
    return jnp.where(exists, vecs @ q, 0.0)


def script_cosine_scores(vecs, exists, q):
    """`script_score: cosineSimilarity(params.query_vector, 'field') + 1.0`
    — the ES idiom for non-negative cosine ranking (BASELINE config 4)."""
    return jnp.where(exists, cosine_scores(vecs, exists, q) + 1.0, 0.0)
