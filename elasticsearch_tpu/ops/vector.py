"""Dense-vector scoring — brute-force exact kNN on the MXU.

f32 is the default (bf16 input rounding visibly reorders near-tie cosine
rankings — recall parity first); pass use_bf16=True to trade exactness for
~2x MXU throughput when the corpus tolerates it.

The reference era has no dense_vector type; its equivalent is binary doc
values + script cosine (BASELINE.md config 4,
core/common/lucene/search/function/ScriptScoreFunction.java). Here vectors
are first-class [N, D] matrices: batched cosine/dot scoring is a single
bf16 matmul — exactly what the 128×128 systolic array is built for.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def l2_normalize(x, axis=-1, eps=1e-12):
    return x / jnp.sqrt((x * x).sum(axis=axis, keepdims=True) + eps)


def cosine_scores(vecs, exists, q, use_bf16: bool = False):
    """Cosine similarity of one query vector against all docs.

    vecs: [N, D] f32 (pre-normalized at reader build); q: [D] f32.
    Returns scores[N] f32 in [-1, 1]; non-existent rows score 0.
    """
    qn = l2_normalize(q)
    if use_bf16:
        s = (vecs.astype(jnp.bfloat16) @ qn.astype(jnp.bfloat16)).astype(jnp.float32)
    else:
        s = vecs @ qn
    return jnp.where(exists, s, 0.0)


def cosine_scores_batch(vecs, exists, qs, use_bf16: bool = False):
    """qs: [Q, D] → scores [Q, N]. One MXU matmul for the whole batch."""
    qn = l2_normalize(qs, axis=-1)
    if use_bf16:
        s = (qn.astype(jnp.bfloat16) @ vecs.astype(jnp.bfloat16).T).astype(jnp.float32)
    else:
        s = qn @ vecs.T
    return jnp.where(exists[None, :], s, 0.0)


def dot_scores(vecs, exists, q):
    return jnp.where(exists, vecs @ q, 0.0)


def cosine_scores_int8_batch(qvecs, scale, offset, exists, qs):
    """Batched cosine over an int8-quantized column.

    qvecs: [N, D] int8 with ``v ≈ q·scale + offset`` per component
    (per-segment scale/offset snapshot); qs: [Q, D] f32 row-normalized.
    The dequantized dot expands to ``scale·(qint·qn) + offset·Σqn`` —
    one matmul on the dense integer column plus a rank-1 correction, so
    the column stays int8-dense in HBM (~4× the f32 corpus capacity).
    → scores [Q, N] f32; non-existent rows score 0.
    """
    qn = l2_normalize(qs, axis=-1)
    s = (qn @ qvecs.astype(jnp.float32).T) * scale \
        + offset * qn.sum(axis=-1, keepdims=True)
    return jnp.where(exists[None, :], s, 0.0)


def filtered_topk_batch(scores, masks, k: int, doc_base: int = 0):
    """Batched filtered-kNN candidate selection: per-query top-k over
    pre-computed score rows with per-query eligibility masks (exists ∧
    live ∧ knn-filter) — the candidate-oversample step of the knn lane
    (``num_candidates`` rows per segment survive to the merge).
    ``lax.top_k`` batches over leading axes natively, so the whole
    batch is one fused selection (stable: ties → lower doc id).

    scores: [B, N] f32; masks: [B, N] bool → ([B, k] f32, [B, k] i32).
    """
    neg_inf = jnp.float32(-jnp.inf)
    masked = jnp.where(masks, scores, neg_inf)
    kk = min(k, masked.shape[-1])
    ts, idx = jax.lax.top_k(masked, kk)
    valid = ts > neg_inf
    td = jnp.where(valid, idx.astype(jnp.int32) + doc_base, -1)
    ts = jnp.where(valid, ts, neg_inf)
    if kk < k:    # corpus smaller than k: pad to the static width
        ts = jnp.pad(ts, ((0, 0), (0, k - kk)), constant_values=neg_inf)
        td = jnp.pad(td, ((0, 0), (0, k - kk)), constant_values=-1)
    return ts, td


def script_cosine_scores(vecs, exists, q):
    """`script_score: cosineSimilarity(params.query_vector, 'field') + 1.0`
    — the ES idiom for non-negative cosine ranking (BASELINE config 4)."""
    return jnp.where(exists, cosine_scores(vecs, exists, q) + 1.0, 0.0)
