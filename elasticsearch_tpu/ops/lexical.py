"""Lexical (BM25) scoring over the forward impact index.

The TPU replacement for Lucene's TermScorer/BooleanScorer postings iteration
(the hot loop behind core/search/query/QueryPhase.java:314): instead of
walking per-term postings lists, every doc row's unique-term array is
compared against the query terms — a dense [N, U]×[T] compare/reduce that
maps straight onto the VPU with zero scatter/gather, exact BM25 scores
(BM25S-style eager scoring, PAPERS.md).
"""

from __future__ import annotations

import jax.numpy as jnp


def bm25_match(uterms, utf, doc_len, qtids, qidf, qweight, k1, b, avgdl):
    """Score a (multi-term, OR-semantics) match query against one segment.

    Args:
      uterms:  [N, U] int32  unique term ids per doc (-1 pad)
      utf:     [N, U] f32    term frequency of each unique term
      doc_len: [N]    i32    field length per doc
      qtids:   [T]    int32  per-segment term ids of query terms (-1 = absent)
      qidf:    [T]    f32    idf per query term (0 for absent/padding)
      qweight: [T]    f32    per-term boost (match queries use 1.0)
      k1, b:   BM25 params (python floats — static under jit)
      avgdl:   f32 scalar    average field length (aggregated host-side)

    Returns:
      scores:  [N] f32  Σ_t idf_t · tfNorm(tf_t,d)
      nmatch:  [N] i32  number of distinct query terms matching each doc
               (drives minimum_should_match / operator=and)
    """
    n = uterms.shape[0]
    norm = k1 * (1.0 - b + b * doc_len.astype(jnp.float32) / avgdl)   # [N]
    tf_norm = utf * (k1 + 1.0) / (utf + norm[:, None])                # [N, U]
    scores = jnp.zeros(n, dtype=jnp.float32)
    nmatch = jnp.zeros(n, dtype=jnp.int32)
    T = qtids.shape[0]
    for t in range(T):  # T is static; unrolled and fused by XLA
        tid = qtids[t]
        hit = (uterms == tid) & (tid >= 0)                            # [N, U]
        any_hit = hit.any(axis=1)
        scores = scores + qidf[t] * qweight[t] * jnp.where(
            any_hit, (tf_norm * hit).sum(axis=1), 0.0)
        nmatch = nmatch + any_hit.astype(jnp.int32)
    return scores, nmatch


def term_filter(uterms, qtid):
    """Pure term-presence mask (filter context: no scoring).

    uterms: [N, U] int32; qtid: scalar int32 (-1 = absent → all False).
    """
    return ((uterms == qtid) & (qtid >= 0)).any(axis=1)


def classic_match(uterms, utf, doc_len, qtids, qidf, qweight):
    """Classic TF-IDF scoring (ref: Lucene TFIDFSimilarity / the 2.x
    "default" similarity): score_t = sqrt(tf) * idf^2 * (1/sqrt(dl)).
    `qidf` carries the CLASSIC idf (1 + ln(N/(df+1))); same interface as
    bm25_match."""
    n = uterms.shape[0]
    inv_norm = jnp.where(doc_len > 0,
                         1.0 / jnp.sqrt(doc_len.astype(jnp.float32)), 0.0)
    scores = jnp.zeros(n, dtype=jnp.float32)
    nmatch = jnp.zeros(n, dtype=jnp.int32)
    for t in range(qtids.shape[0]):
        tid = qtids[t]
        hit = (uterms == tid) & (tid >= 0)
        any_hit = hit.any(axis=1)
        tf = (utf * hit).sum(axis=1)
        scores = scores + qweight[t] * (qidf[t] * qidf[t]) * jnp.where(
            any_hit, jnp.sqrt(tf) * inv_norm, 0.0)
        nmatch = nmatch + any_hit.astype(jnp.int32)
    return scores, nmatch


def lm_dirichlet_match(uterms, utf, doc_len, qtids, qctf_frac, qweight,
                       mu):
    """LM Dirichlet smoothing (ref: Lucene LMDirichletSimilarity, the
    reference's lm_dirichlet similarity module): per matched term
    score_t = log(1 + tf/(mu * P(t|C))) + log(mu / (dl + mu)), floored at
    0 like Lucene. `qctf_frac` = collection term frequency / collection
    token count per query term."""
    n = uterms.shape[0]
    dl = doc_len.astype(jnp.float32)
    norm = jnp.log(mu / (dl + mu))                                    # [N]
    scores = jnp.zeros(n, dtype=jnp.float32)
    nmatch = jnp.zeros(n, dtype=jnp.int32)
    for t in range(qtids.shape[0]):
        tid = qtids[t]
        hit = (uterms == tid) & (tid >= 0)
        any_hit = hit.any(axis=1)
        tf = (utf * hit).sum(axis=1)
        term_score = jnp.log1p(tf / (mu * jnp.maximum(qctf_frac[t],
                                                      1e-12))) + norm
        scores = scores + qweight[t] * jnp.where(
            any_hit, jnp.maximum(term_score, 0.0), 0.0)
        nmatch = nmatch + any_hit.astype(jnp.int32)
    return scores, nmatch
