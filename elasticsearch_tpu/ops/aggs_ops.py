"""Aggregation reduction kernels.

The reference builds a per-segment collector tree that increments bucket
counters doc-by-doc (core/search/aggregations/Aggregator.java,
AggregationPhase.java:44) over BigArrays. On TPU the same reductions are
masked dense ops over doc-values columns: terms agg = segment_sum over
ordinals, metrics = masked reductions, histogram = bucketize + segment_sum.
Per-segment partials are merged host-side through the segment→shard→global
reduce (InternalAggregations.reduce analog, search/aggregations.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def ord_value_counts(ords, mask, num_ords: int):
    """Terms-agg kernel: per-ordinal doc-value counts.

    ords: [N, K] int32 (-1 pad); mask: [N] bool (docs in agg context).
    num_ords: static (padded) vocab size. → counts [num_ords] int32.
    """
    valid = (ords >= 0) & mask[:, None]
    flat_ords = jnp.where(valid, ords, num_ords).reshape(-1)  # overflow slot
    ones = valid.astype(jnp.int32).reshape(-1)
    counts = jax.ops.segment_sum(ones, flat_ords, num_segments=num_ords + 1)
    return counts[:num_ords]


def ord_metric_sums(ords, mask, metric_values, num_ords: int):
    """Per-ordinal sum of a metric column (sub-aggregation support):
    e.g. terms agg bucket → avg(price). → sums [num_ords] f64-ish f32."""
    valid = (ords >= 0) & mask[:, None]
    flat_ords = jnp.where(valid, ords, num_ords).reshape(-1)
    vals = jnp.where(valid, metric_values[:, None], 0.0).reshape(-1)
    sums = jax.ops.segment_sum(vals, flat_ords, num_segments=num_ords + 1)
    return sums[:num_ords]


def histogram_counts(values, exists, mask, base: float, interval: float,
                     num_buckets: int):
    """Histogram kernel. Bucket i covers [base + i·interval, base+(i+1)·interval).
    base/num_buckets are computed host-side from a min/max pre-pass."""
    in_ctx = exists & mask
    idx = jnp.floor((values - base) / interval).astype(jnp.int32)
    idx = jnp.where(in_ctx & (idx >= 0) & (idx < num_buckets), idx, num_buckets)
    ones = jnp.where(idx < num_buckets, 1, 0)
    counts = jax.ops.segment_sum(ones, idx, num_segments=num_buckets + 1)
    return counts[:num_buckets]


def histogram_counts_dd(hi, lo, exists, mask, base_hi: float, base_lo: float,
                        interval: float, num_buckets: int):
    """Histogram over double-double values (epoch-millis dates, large
    longs): f32 alone quantizes 1.5e12 to ~1e5 steps, so bucketize the
    RELATIVE value (hi - base_hi) + (lo - base_lo) — exact to f32 epsilon
    of the data RANGE, not of the absolute magnitude (Sterbenz: same-scale
    f32 subtraction is exact). base must sit at/below the minimum value,
    on a bucket boundary, split host-side via dd_split."""
    in_ctx = exists & mask
    rel = (hi - jnp.float32(base_hi)) + (lo - jnp.float32(base_lo))
    idx = jnp.floor(rel / jnp.float32(interval)).astype(jnp.int32)
    idx = jnp.where(in_ctx & (idx >= 0) & (idx < num_buckets), idx,
                    num_buckets)
    ones = jnp.where(idx < num_buckets, 1, 0)
    counts = jax.ops.segment_sum(ones, idx, num_segments=num_buckets + 1)
    return counts[:num_buckets]


def range_counts(values, exists, mask, lows, highs):
    """range agg: lows/highs [R] f64 device arrays (±inf open ends).
    → counts [R] int32 (ranges may overlap, matching ES semantics)."""
    in_ctx = (exists & mask)[:, None]
    hit = in_ctx & (values[:, None] >= lows[None, :]) & (values[:, None] < highs[None, :])
    return hit.sum(axis=0).astype(jnp.int32)


def dd_min_max(hi, lo, exists, mask):
    """Exact extrema of a double-double column by lexicographic (hi, lo)
    order — a bare f32 hi min/max is off by up to half an ulp of the
    magnitude (~65 s at epoch-millis scale). → (count, min_hi, min_lo,
    max_hi, max_lo) device scalars; host reconstructs exact f64 as
    hi + lo."""
    m = exists & mask
    cnt = m.sum(dtype=jnp.int32)
    mn_hi = jnp.min(jnp.where(m, hi, jnp.inf))
    mn_lo = jnp.min(jnp.where(m & (hi == mn_hi), lo, jnp.inf))
    mx_hi = jnp.max(jnp.where(m, hi, -jnp.inf))
    mx_lo = jnp.max(jnp.where(m & (hi == mx_hi), lo, -jnp.inf))
    return cnt, mn_hi, mn_lo, mx_hi, mx_lo


def stats_metrics(values, exists, mask):
    """min/max/sum/count in one pass (stats agg; avg derived host-side)."""
    m = exists & mask
    cnt = m.sum(dtype=jnp.int32)
    s = jnp.where(m, values, 0.0).sum()
    mn = jnp.min(jnp.where(m, values, jnp.inf))
    mx = jnp.max(jnp.where(m, values, -jnp.inf))
    return cnt, s, mn, mx


def sum_of_squares(values, exists, mask):
    """extended_stats: Σv² (variance/std derived host-side)."""
    m = exists & mask
    return jnp.where(m, values * values, 0.0).sum()


def value_count(exists, mask):
    return (exists & mask).sum(dtype=jnp.int32)


def cardinality_ords(ords, mask, num_ords: int):
    """Exact distinct ordinal count within this segment. Cross-segment union
    is resolved host-side via vocab strings (exact, unlike the reference's
    HLL++ — core/search/aggregations/metrics/cardinality/)."""
    present = ord_value_counts(ords, mask, num_ords) > 0
    return present, present.sum(dtype=jnp.int32)


def masked_sort_values(values, exists, mask, fill: float = jnp.inf):
    """Sorted live values (percentiles agg: exact quantiles from the sorted
    array; host interpolates). Fill sinks non-context docs to the end."""
    m = exists & mask
    return jnp.sort(jnp.where(m, values, fill)), m.sum(dtype=jnp.int32)
