"""Postings-block BM25 scoring — the inverted alternative to the forward scan.

Two batched BM25 top-k kernels living behind the same contract as
``models/bm25.bm25_topk_batch`` (the Lucene TermScorer replacement,
ref: core/search/query/QueryPhase.java:314), each with a different
work/hardware trade-off. ``ROOFLINE.md`` at the repo root derives the
arithmetic; the bench (bench.py, BENCH_KERNEL=forward|slots|csr) measures
all three on the chip and the engine keeps whichever wins.

1. **slots** (`bm25_topk_batch_slots`): forward-layout scan, restructured so
   the per-doc work is shared across the whole query batch. The batch's
   unique terms become S "slots"; one pass over the [N, U] forward index
   builds a per-doc slot-impact matrix A[N, S] (VPU compare+accumulate),
   then every query's scores come from one MXU matmul W[Q, S] @ A[N, S]^T.
   Work: N·U·S VPU ops + N·S·Q MXU MACs per batch — independent of how
   many queries share terms, and the doc axis is processed in fixed-size
   blocks with a running top-k, so HBM stays O(block·S + Q·k) at any N.

2. **csr** (`bm25_topk_batch_csr`): true postings (impact-block) layout —
   a term-partitioned CSR built once per segment; scoring gathers only the
   postings of the batch's terms (E = Σ df entries) and scatter-adds
   weighted impacts into dense [Q, N] score rows. Work: O(E) gathers +
   Q·E scatter-adds — asymptotically the CPU/Lucene work profile, but
   scatter throughput on TPU is the open question the bench answers.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = jnp.float32(-jnp.inf)


# ---------------------------------------------------------------------------
# Host-side batch planning (shared by both kernels)
# ---------------------------------------------------------------------------

def plan_batch(qtids: np.ndarray, qidf: np.ndarray, vocab_size: int,
               qweight: np.ndarray | None = None,
               slot_pad: int = 32, s_total: int | None = None):
    """Map a [Q, T] query batch onto batch-unique term slots.

    Returns (table [V+1] int32: term id -> slot, or S for absent;
             W [Q, S] f32: per-query per-slot weight = idf·boost summed over
             duplicate query terms — Lucene sums duplicate TermQuery clauses).
    S is padded to a multiple of ``slot_pad`` (or to the fixed ``s_total``)
    to bound compiled shapes: steady-state serving should pass a fixed
    ``s_total`` (e.g. Q·T rounded up) so every batch hits one compiled
    program.
    """
    q, t = qtids.shape
    uniq = np.unique(qtids[qtids >= 0])
    s_real = uniq.shape[0]
    if s_total is not None:
        if s_real > s_total:
            raise ValueError(f"batch has {s_real} unique terms > "
                             f"s_total={s_total}")
        s = s_total
    else:
        s = max(((s_real + slot_pad - 1) // slot_pad) * slot_pad, slot_pad)
    table = np.full(vocab_size + 1, s, np.int32)
    table[uniq] = np.arange(s_real, dtype=np.int32)
    w = np.zeros((q, s), np.float32)
    if qweight is None:
        qweight = np.ones_like(qidf)
    rows = np.repeat(np.arange(q), t)
    valid = (qtids >= 0).reshape(-1)
    slots = table[np.clip(qtids.reshape(-1), 0, vocab_size)]
    np.add.at(w, (rows[valid], slots[valid]),
              (qidf * qweight).reshape(-1)[valid])
    return table, w


# ---------------------------------------------------------------------------
# Kernel 1: slot-shared forward scan (VPU build + MXU weighting)
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("k", "k1", "b", "block"))
def bm25_topk_batch_slots(uterms, utf, doc_len, live, table, w, avgdl,
                          k: int, k1: float = 1.2, b: float = 0.75,
                          block: int = 16384):
    """Batched BM25 top-k via batch-shared slot impacts.

    uterms/utf: [N, U] forward impact index; doc_len/live: [N];
    table: [V+1] int32 term→slot; w: [Q, S] f32 per-query slot weights.
    Returns (top_scores [Q, k], top_docs [Q, k] int32 global doc ids).
    """
    n, u = uterms.shape
    q, s = w.shape
    blk = min(block, n)
    if n % blk:
        # callers normally supply bucketized (power-of-2) row counts so a
        # power-of-2 block divides evenly; pad defensively otherwise
        pad = blk - n % blk
        uterms = jnp.pad(uterms, ((0, pad), (0, 0)), constant_values=-1)
        utf = jnp.pad(utf, ((0, pad), (0, 0)))
        doc_len = jnp.pad(doc_len, (0, pad), constant_values=1)
        live = jnp.pad(live, (0, pad))
        n += pad
    n_blocks = n // blk
    kk = min(k, n)

    norm = k1 * (1.0 - b + b * doc_len.astype(jnp.float32) / avgdl)
    has_term = w > 0.0                # [Q, S] one indicator per query term
    slot_ids = jnp.arange(s)

    def body(carry, i):
        top_s, top_d = carry
        ut = jax.lax.dynamic_slice(uterms, (i * blk, 0), (blk, u))
        tf = jax.lax.dynamic_slice(utf, (i * blk, 0), (blk, u))
        nm = jax.lax.dynamic_slice(norm, (i * blk,), (blk,))
        lv = jax.lax.dynamic_slice(live, (i * blk,), (blk,))
        tfn = tf * (k1 + 1.0) / (tf + nm[:, None])            # [B, U]
        slot = table[jnp.clip(ut, 0, table.shape[0] - 2)]
        slot = jnp.where(ut >= 0, slot, s)                    # pad → S

        # accumulate slot impacts one unique-term column at a time so the
        # transient stays [B, S] (never [B, U, S]): VPU compare+FMA chain
        def acc(j, carry_a):
            a_acc, pres = carry_a
            hit = slot[:, j][:, None] == slot_ids[None, :]    # [B, S]
            a_acc = a_acc + jnp.where(hit, tfn[:, j][:, None], 0.0)
            return a_acc, pres | hit

        a, present = jax.lax.fori_loop(
            0, u, acc, (jnp.zeros((blk, s), jnp.float32),
                        jnp.zeros((blk, s), bool)))
        scores = jax.lax.dot_general(
            w, a, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [Q, B]
        matched = jax.lax.dot_general(
            has_term.astype(jnp.float32), present.astype(jnp.float32),
            (((1,), (1,)), ((), ())))                         # [Q, B]
        ok = lv[None, :] & (matched > 0.0)
        masked = jnp.where(ok, scores, NEG_INF)
        bs, bi = jax.lax.top_k(masked, min(kk, blk))          # [Q, kb]
        bd = jnp.where(bs > NEG_INF,
                       (bi + i * blk).astype(jnp.int32), -1)
        # merge with running top-k (stable: earlier blocks first keeps
        # doc-id-ascending tie-break, matching TopDocs.merge)
        cat_s = jnp.concatenate([top_s, bs], axis=1)
        cat_d = jnp.concatenate([top_d, bd], axis=1)
        ms, mi = jax.lax.top_k(cat_s, kk)
        md = jnp.take_along_axis(cat_d, mi, axis=1)
        return (ms, md), None

    init = (jnp.full((q, kk), NEG_INF), jnp.full((q, kk), -1, jnp.int32))
    (top_s, top_d), _ = jax.lax.scan(body, init, jnp.arange(n_blocks))
    if kk < k:
        top_s = jnp.pad(top_s, ((0, 0), (0, k - kk)),
                        constant_values=NEG_INF)
        top_d = jnp.pad(top_d, ((0, 0), (0, k - kk)), constant_values=-1)
    return top_s, top_d


# ---------------------------------------------------------------------------
# Kernel 2: CSR postings gather + scatter-add
# ---------------------------------------------------------------------------

class PostingsIndex:
    """Term-partitioned CSR over a segment's forward index (host build).

    The inverted view of the [N, U] forward columns: per term, the doc ids
    containing it and their term frequencies, concatenated in term order —
    Lucene's postings lists as three dense arrays (SURVEY.md §7 step 2's
    "postings as padded dense blocks").
    """

    def __init__(self, indptr: np.ndarray, docs: np.ndarray,
                 tfs: np.ndarray):
        self.indptr = indptr          # [V+1] int64
        self.docs = docs              # [NNZ] int32, doc-sorted per term
        self.tfs = tfs                # [NNZ] float32

    @staticmethod
    def from_forward(uterms: np.ndarray, utf: np.ndarray,
                     vocab_size: int) -> "PostingsIndex":
        valid = uterms >= 0
        terms = uterms[valid].astype(np.int64)
        rows = np.broadcast_to(
            np.arange(uterms.shape[0], dtype=np.int32)[:, None],
            uterms.shape)[valid]
        tfs = utf[valid]
        order = np.argsort(terms, kind="stable")  # doc order preserved per term
        terms, rows, tfs = terms[order], rows[order], tfs[order]
        counts = np.bincount(terms, minlength=vocab_size)
        indptr = np.zeros(vocab_size + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        return PostingsIndex(indptr, rows.astype(np.int32),
                             tfs.astype(np.float32))

    def gather_batch(self, table: np.ndarray, s: int,
                     pad_to: int = 4096):
        """Concatenate the postings of the batch's S slot terms.

        Returns (entry_slot [E] int32, entry_doc [E] int32,
        entry_tf [E] f32) with E padded to ``pad_to`` granularity
        (pad entries have slot == s and doc == 0, weight 0 via W).
        """
        tids = np.nonzero(table[:-1] < s)[0]
        spans = [(int(self.indptr[t]), int(self.indptr[t + 1]),
                  int(table[t])) for t in tids]
        e = sum(hi - lo for lo, hi, _ in spans)
        ep = max(((e + pad_to - 1) // pad_to) * pad_to, pad_to)
        entry_slot = np.full(ep, s, np.int32)
        entry_doc = np.zeros(ep, np.int32)
        entry_tf = np.zeros(ep, np.float32)
        at = 0
        for lo, hi, slot in spans:
            w = hi - lo
            entry_slot[at:at + w] = slot
            entry_doc[at:at + w] = self.docs[lo:hi]
            entry_tf[at:at + w] = self.tfs[lo:hi]
            at += w
        return entry_slot, entry_doc, entry_tf


@partial(jax.jit, static_argnames=("k", "k1", "b", "n_docs"))
def bm25_topk_batch_csr(entry_slot, entry_doc, entry_tf, doc_len, live,
                        w, avgdl, n_docs: int, k: int,
                        k1: float = 1.2, b: float = 0.75):
    """Scatter-add postings scoring: O(E) work like the CPU baseline.

    entry_*: [E] flattened batch postings (slot, doc, tf); w: [Q, S+1]
    weights with a zero pad column at S. Returns (scores [Q, k], docs).
    """
    q = w.shape[0]
    norm = k1 * (1.0 - b + b * doc_len.astype(jnp.float32) / avgdl)
    contrib = entry_tf * (k1 + 1.0) / (entry_tf + norm[entry_doc])  # [E]

    def one(w_q):
        vals = w_q[entry_slot] * contrib
        scores = jnp.zeros(n_docs, jnp.float32).at[entry_doc].add(
            vals, mode="drop")
        return scores

    scores = jax.vmap(one)(w)                                    # [Q, N]
    masked = jnp.where(live[None, :] & (scores > 0.0), scores, NEG_INF)
    kk = min(k, n_docs)
    top_s, top_i = jax.lax.top_k(masked, kk)
    top_d = jnp.where(top_s > NEG_INF, top_i.astype(jnp.int32), -1)
    if kk < k:
        top_s = jnp.pad(top_s, ((0, 0), (0, k - kk)),
                        constant_values=NEG_INF)
        top_d = jnp.pad(top_d, ((0, 0), (0, k - kk)), constant_values=-1)
    return top_s, top_d
