"""Span algebra over the position-indexed token matrix.

Lucene's span queries (core/index/query/Span*QueryParser.java → Lucene
``spans`` package) enumerate (start, end) position intervals per doc and
combine them (or / not / first / near / containing / within). The
TPU-native representation here is the **min-end map**: for every start
position ``p`` of a doc, ``ends[doc, p]`` holds the SMALLEST end of a span
starting at ``p`` (``INF`` when no span starts there). All combinators are
dense [N, L] array ops — no per-doc iteration.

Exactness: unit-width leaves (span_term, span_multi expansions and
span_or over them) make every combinator exact — including unordered
span_near, which composes arbitrarily (NearSpansUnordered semantics:
window width minus total span length ≤ slop, anchored at clause
starts). Clauses that produce multi-width span sets (a sloppy
span_near nested inside another combinator) are represented by their
minimal span per start — a documented approximation (the non-minimal
alternatives are dropped, like keeping only the first span per start
position).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

INF = jnp.int32(1 << 30)


def term_ends(tokens, tid):
    """[N, L] token matrix + scalar term id → min-end map (unit spans)."""
    pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
    return jnp.where((tokens == tid) & (tid >= 0), pos + 1, INF)


def term_set_ends(tokens, tids):
    """Unit spans at positions whose token is in ``tids`` ([T], -1 pad) —
    the span_multi rewrite (SpanMultiTermQueryWrapper)."""
    hit = (tokens[:, :, None] == tids[None, None, :]) & \
        (tids[None, None, :] >= 0)
    pos = jnp.arange(tokens.shape[1], dtype=jnp.int32)[None, :]
    return jnp.where(hit.any(axis=2), pos + 1, INF)


def pad_ends(ends, L: int):
    """Pad the position axis to a common L (no spans start in padding)."""
    if ends.shape[1] == L:
        return ends
    return jnp.pad(ends, ((0, 0), (0, L - ends.shape[1])),
                   constant_values=INF)


def or_ends(ends_list):
    """Union of span sets — min of min-ends per start (SpanOrQuery)."""
    return functools.reduce(jnp.minimum, ends_list)


def first_ends(ends, end: int):
    """Spans ending at position ≤ ``end`` (SpanFirstQuery)."""
    return jnp.where(ends <= jnp.int32(end), ends, INF)


def _first_start_from(ends):
    """F[q] = earliest start ≥ q with a span (else INF) — suffix min of
    start positions."""
    pos = jnp.arange(ends.shape[1], dtype=jnp.int32)[None, :]
    idx = jnp.where(ends < INF, pos, INF)
    return jax.lax.cummin(idx, axis=1, reverse=True)


def near_unordered_ends(ends_list, slop: int):
    """Unordered near over span clauses → min-end map (SpanNearQuery
    in_order=false, NearSpansUnordered): a window starts at ``p`` when
    some clause's span starts exactly at p and EVERY clause has a span
    inside the window; the Lucene slop criterion is
    (window_end − window_start) − Σ span widths ≤ slop. Each clause
    greedily takes its earliest span starting ≥ p (exact for unit-width
    clauses, minimal-span approximation for nested multi-width ones —
    the same representation discipline as the ordered combinator)."""
    L = ends_list[0].shape[1]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    window_end = None
    total_len = jnp.zeros_like(ends_list[0])
    anchored = jnp.zeros(ends_list[0].shape, bool)
    valid = jnp.ones(ends_list[0].shape, bool)
    for ek in ends_list:
        fk = _first_start_from(ek)           # earliest start ≥ p
        sk = fk
        e_at = jnp.where(
            sk < INF,
            jnp.take_along_axis(ek, jnp.clip(sk, 0, L - 1), axis=1), INF)
        valid = valid & (sk < INF)
        anchored = anchored | (ek < INF)     # a span starts AT p
        window_end = e_at if window_end is None \
            else jnp.maximum(window_end, e_at)
        total_len = total_len + jnp.where(sk < INF, e_at - sk, 0)
    ok = valid & anchored & \
        (window_end - pos - total_len <= jnp.int32(slop))
    return jnp.where(ok, window_end, INF)


def near_ordered_ends(ends_list, slop: int):
    """Ordered near over span clauses: chains each clause's EARLIEST
    start ≥ the previous clause's end (greedy — exact for unit-width
    clauses), total inter-span gap ≤ slop (SpanNearQuery in_order)."""
    L = ends_list[0].shape[1]
    cur_end = ends_list[0]
    valid = cur_end < INF
    total_gap = jnp.zeros_like(cur_end)
    for ek in ends_list[1:]:
        fk = _first_start_from(ek)
        in_range = valid & (cur_end < L)
        q = jnp.clip(cur_end, 0, L - 1)
        start_k = jnp.where(in_range, jnp.take_along_axis(fk, q, axis=1),
                            INF)
        end_k = jnp.take_along_axis(
            ek, jnp.clip(start_k, 0, L - 1), axis=1)
        valid = in_range & (start_k < INF)
        total_gap = total_gap + jnp.where(valid, start_k - cur_end, 0)
        cur_end = jnp.where(valid, end_k, INF)
    return jnp.where(valid & (total_gap <= jnp.int32(slop)), cur_end, INF)


def coverage(ends):
    """[N, L] bool — positions covered by ANY span of the set (interval
    scatter: +1 at starts, −1 at ends, prefix sum > 0)."""
    n, L = ends.shape
    has = (ends < INF).astype(jnp.int32)
    delta = jnp.zeros((n, L + 1), jnp.int32).at[:, :L].add(has)
    end_idx = jnp.clip(jnp.where(ends < INF, ends, 0), 0, L)
    rows = jnp.arange(n, dtype=jnp.int32)[:, None]
    delta = delta.at[rows, end_idx].add(-has)
    return jnp.cumsum(delta, axis=1)[:, :L] > 0


def not_ends(inc, exc, pre: int, post: int):
    """Include spans whose window [start−pre, end+post) does not touch any
    exclude span (SpanNotQuery)."""
    n, L = inc.shape
    cov = coverage(exc).astype(jnp.int32)
    prefix = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.int32), jnp.cumsum(cov, axis=1)], axis=1)
    pos = jnp.arange(L, dtype=jnp.int32)
    w0 = jnp.clip(pos - pre, 0, L)                       # [L]
    w1 = jnp.clip(jnp.where(inc < INF, inc, 0) + post, 0, L)   # [N, L]
    covered = (jnp.take_along_axis(prefix, w1, axis=1)
               - jnp.take(prefix, w0, axis=1)) > 0
    return jnp.where((inc < INF) & ~covered, inc, INF)


def _shift_left_dyn(a, d, fill):
    """a[:, p] → a[:, p+d] for traced d (out-of-range = fill)."""
    L = a.shape[1]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    return jnp.where(pos < L - d, jnp.roll(a, -d, axis=1), fill)


def _shift_right_dyn(a, d, fill):
    """a[:, p] → a[:, p−d] for traced d (out-of-range = fill)."""
    pos = jnp.arange(a.shape[1], dtype=jnp.int32)[None, :]
    return jnp.where(pos >= d, jnp.roll(a, d, axis=1), fill)


def containing_ends(big, little):
    """Spans of ``big`` containing at least one ``little`` span
    (SpanContainingQuery): big [p, e) contains little [p+d, e') when
    p+d < e and e' ≤ e."""
    L = big.shape[1]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]

    def body(d, acc):
        lsh = _shift_left_dyn(little, d, INF)
        return acc | ((pos + d < big) & (lsh <= big) & (lsh < INF))

    acc = jax.lax.fori_loop(0, L, body,
                            jnp.zeros(big.shape, bool))
    return jnp.where(acc & (big < INF), big, INF)


def within_ends(little, big):
    """Spans of ``little`` lying inside some ``big`` span
    (SpanWithinQuery): little at q with end l is inside big [q−d, e) when
    e ≥ l (start q−d ≤ q holds by construction)."""
    L = little.shape[1]

    def body(d, acc):
        bsh = _shift_right_dyn(big, d, INF)
        return acc | ((bsh < INF) & (bsh >= little) & (little < INF))

    acc = jax.lax.fori_loop(0, L, body,
                            jnp.zeros(little.shape, bool))
    return jnp.where(acc, little, INF)


def span_freq(ends):
    """Span frequency per doc = number of starts with a span (each start
    contributes its minimal span once)."""
    return (ends < INF).sum(axis=1).astype(jnp.float32)
