"""Impact-ordered scoring and block-max pruning kernels.

The forward kernel (ops/lexical.py) recomputes the full BM25 term
contribution — idf · tf·(k1+1)/(tf+norm) — for every (doc, query term)
pair on every query. The impact lane precomputes that product at
segment-build time into a quantized column (index/segment.py
``ImpactColumn``), so query-time scoring collapses to a dense compare +
integer gather/sum (BM25S, PAPERS.md): no per-doc float math, and the
int sums dequantize with ONE multiply per doc.

``pruned_segment_topk`` adds the asymptotic win: rows are organized in
fixed blocks with a per-(block, term) quantized maximum
(GPUSparse-style dense block table), blocks are swept in descending
upper-bound order under ``lax.scan``, and a block whose bound cannot
beat the running k-th score skips its compute AND its HBM reads through
``lax.cond`` — WAND/block-max, expressed with static shapes so XLA
stays happy. Queries run through ``lax.map`` (not vmap) so the cond
remains a real branch instead of degrading to a select.

Correctness contract (tests/test_impact_index.py): both lanes produce
BIT-IDENTICAL scores (integer sums × the same scale), and pruning is
conservative — a block is skipped only when no query term occurs in it
(block_max carries an occupancy floor of 1 on present cells, so even
fully-zero-quantized terms keep their blocks sweepable) or when its
bound is strictly below the current k-th score (ties kept), so the
pruned top-k equals the unpruned top-k exactly, including the
(score desc, doc asc) tie order of the exact scorer's merge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# module-level import, NEVER inside a traced body: an import executed
# at trace time caches foreign tracers into the imported module's
# globals ("compiled for N+3 inputs" under concurrency — the PR 10 bug
# plane-lint's trace-purity family now guards against)
from elasticsearch_tpu.ops import topk as topk_ops

NEG_INF = jnp.float32(-jnp.inf)
#: doc-id sort key for empty slots: past any real doc id so -inf ties
#: never displace real candidates
_PAD_DOC = jnp.int32(1 << 30)

#: term-batch width of the score/bound reductions: terms reduce in
#: chunks of TB through one broadcast compare instead of one unrolled
#: [N, U] pass per term, so program size grows with ceil(T/TB) — the
#: widened 64-term admission cap (expansion-sized queries, the SPLADE
#: arm's pre-work) compiles to 8 fused passes instead of 64
_TERM_BATCH = 8


def impact_scores(uterms, qimp, qtids):
    """Quantized eager scoring of one query against impact columns.

    uterms: [N, U] i32 (-1 pad); qimp: [N, U] uint8/16 quantized
    impacts; qtids: [T] i32 per-segment term ids (-1 absent/pad).

    → (qsum [N] i32 — Σ of matched quantized impacts, exact integer
    arithmetic; anyhit [N] bool — OR-semantics match mask, identical to
    the exact kernel's msm1 mask).

    Score and match count share ONE reduction per term chunk: each
    entry packs ``(q << 8) | 1`` so the sum carries Σq in the high bits
    and the match count in the low byte — halving the [N, U, TB]
    reduction passes vs separate sum + any. Exact because uterms slots
    are UNIQUE per doc (≤ 1 hit per term per doc → count ≤ T ≤ 255) and
    Σq·256 + T stays inside int32 for the validated caps (T ≤ 255 at
    8-bit impacts, T ≤ 127 at 16-bit — validate_impact_settings pins
    both). Integer addition is associative, so the chunked sum is
    bit-identical to the per-term unroll at any chunk width."""
    n = uterms.shape[0]
    enc = (qimp.astype(jnp.int32) << 8) + 1
    acc = jnp.zeros(n, jnp.int32)
    t = qtids.shape[0]                # static: chunk count fixed at trace
    for lo in range(0, t, _TERM_BATCH):
        chunk = qtids[lo:lo + _TERM_BATCH]            # [C] i32
        hit = (uterms[:, :, None] == chunk[None, None, :]) & \
            (chunk >= 0)[None, None, :]               # [N, U, C]
        acc = acc + jnp.where(hit, enc[:, :, None], 0).sum(axis=(1, 2))
    return acc >> 8, (acc & 0xFF) > 0


def block_bounds(block_max, qtids):
    """Per-block integer upper bounds: Σ_t block_max[:, t] over the
    query terms, reduced in the same :data:`_TERM_BATCH` chunks as
    :func:`impact_scores`. ≥ every in-block quantized score (per-term
    max is an upper bound of per-term contribution; sums preserve it —
    the occupancy floor of 1 on present cells only loosens the bound by
    one quantization unit per term). Because absent cells are exactly 0
    and present cells ≥ 1, ``ub > 0`` ⟺ some query term OCCURS in the
    block — the presence test the pruning sweep keys its skip on."""
    nb = block_max.shape[0]
    ub = jnp.zeros(nb, jnp.int32)
    t = qtids.shape[0]
    for lo in range(0, t, _TERM_BATCH):
        chunk = qtids[lo:lo + _TERM_BATCH]            # [C] i32
        cols = jnp.take(block_max, jnp.maximum(chunk, 0),
                        axis=1).astype(jnp.int32)     # [NB, C]
        ub = ub + jnp.where((chunk >= 0)[None, :], cols, 0).sum(axis=1)
    return ub


def topk_flat_by_doc(scores, docs, k: int):
    """Top-k of ONE flat candidate list by (score desc, doc id asc) —
    the exact scorer's tie order. Empty slots: (-inf, -1). Lists
    shorter than k pad out; candidates carrying GLOBAL doc ids keep
    them (the cross-chip merge re-selects gathered per-shard top-k
    lists through this, so the mesh lanes' final order is the same
    doc-asc tie-break the single-chip merge applies)."""
    n = scores.shape[0]
    if n < k:
        scores = jnp.pad(scores, (0, k - n), constant_values=NEG_INF)
        docs = jnp.pad(docs, (0, k - n), constant_values=-1)
    key_d = jnp.where(docs >= 0, docs, _PAD_DOC)
    by_doc = jnp.argsort(key_d)                       # doc asc
    by_score = jnp.argsort(-scores[by_doc])           # stable: doc ties
    sel = by_doc[by_score][:k]
    ts = scores[sel]
    return ts, jnp.where(ts > NEG_INF, docs[sel], -1)


def merge_topk_by_doc(scores_a, docs_a, scores_b, docs_b, k: int):
    """Top-k of the concatenation by (score desc, doc id asc) — the
    exact scorer's merge tie order, made explicit because block-sweep
    candidates arrive out of doc order. Empty slots: (-inf, -1)."""
    s = jnp.concatenate([scores_a, scores_b])
    d = jnp.concatenate([docs_a, docs_b])
    return topk_flat_by_doc(s, d, k)


def eager_segment_topk(uterms, qimp, live, qtids, scale_boost, k: int,
                       doc_base: int, cursor_s, cursor_d):
    """One query × one segment, full (unpruned) impact scoring.

    → (top_scores [k] f32, top_docs [k] i32 segment-LOCAL, count i32).
    ``scale_boost`` = segment dequant scale × query boost (traced);
    ``cursor_s``/``cursor_d`` implement the score-order search_after
    continuation (pass +inf / -1 for no cursor)."""
    n = uterms.shape[0]
    qsum, anyhit = impact_scores(uterms, qimp, qtids)
    sf = qsum.astype(jnp.float32) * scale_boost
    gids = jnp.arange(n, dtype=jnp.int32) + doc_base
    valid = anyhit & live & \
        ((sf < cursor_s) | ((sf == cursor_s) & (gids > cursor_d)))
    count = valid.sum(dtype=jnp.int32)
    ts, td = topk_ops.top_k(sf, valid, min(k, n), 0)
    return ts, td, count


def pruned_segment_topk(carry, uterms, qimp, live, block_max, qtids,
                        scale_boost, k: int, doc_base: int,
                        cursor_s, cursor_d):
    """One query's block-max sweep over one segment, threading the
    running top-k across segments.

    carry = (top_scores [k] f32, top_docs [k] i32 GLOBAL, scored i32,
    skipped i32, matched i32). Blocks are visited in descending
    upper-bound order; a block runs only when its bound can still reach
    the k-th score (``ub >= θ`` — non-strict, so boundary ties survive)
    AND some query term occurs in it at all (``ub_i > 0`` — exact
    PRESENCE, not a score test: block_max stores present cells with a
    floor of 1, so a term whose impacts all quantize to 0 still runs
    its blocks and its score-0 hits match the eager lane's anyhit
    mask). The skipped branch touches none of the block's rows
    (lax.cond): on real hardware that is skipped compute AND skipped
    HBM reads."""
    np_docs, u = uterms.shape
    n_blocks = block_max.shape[0]
    r = np_docs // n_blocks
    ub_i = block_bounds(block_max, qtids)
    ub_f = ub_i.astype(jnp.float32) * scale_boost
    order = jnp.argsort(-ub_f)

    def step(c, bi):
        ts, td, n_scored, n_skipped, n_matched = c
        theta = ts[k - 1]
        run = (ub_i[bi] > 0) & (ub_f[bi] >= theta)

        def hot(c):
            ts, td, n_scored, n_skipped, n_matched = c
            ru = jax.lax.dynamic_slice(uterms, (bi * r, 0), (r, u))
            rq = jax.lax.dynamic_slice(qimp, (bi * r, 0), (r, u))
            rl = jax.lax.dynamic_slice(live, (bi * r,), (r,))
            qsum, anyhit = impact_scores(ru, rq, qtids)
            sf = qsum.astype(jnp.float32) * scale_boost
            docs = bi * r + jnp.arange(r, dtype=jnp.int32) + doc_base
            valid = anyhit & rl & \
                ((sf < cursor_s) | ((sf == cursor_s) & (docs > cursor_d)))
            sf = jnp.where(valid, sf, NEG_INF)
            docs = jnp.where(valid, docs, -1)
            ts2, td2 = merge_topk_by_doc(ts, td, sf, docs, k)
            return (ts2, td2, n_scored + 1, n_skipped,
                    n_matched + valid.sum(dtype=jnp.int32))

        def cold(c):
            ts, td, n_scored, n_skipped, n_matched = c
            return (ts, td, n_scored, n_skipped + 1, n_matched)

        return jax.lax.cond(run, hot, cold, c), None

    carry, _ = jax.lax.scan(step, carry, order)
    return carry


def pruned_carry_init(k: int):
    """Fresh cross-segment carry for :func:`pruned_segment_topk`."""
    return (jnp.full(k, NEG_INF, jnp.float32),
            jnp.full(k, -1, jnp.int32),
            jnp.int32(0), jnp.int32(0), jnp.int32(0))


#: θ-exchange cadence of the mesh sweep: the shard-local block order is
#: split into this many chunks, and shards exchange their running k-th
#: score (one ``pmax`` over the shard axis) at each chunk boundary.
#: More rounds → tighter cross-chip pruning, more ICI latency; 4 keeps
#: the exchange cost below one block's HBM read at validated shapes.
THETA_EXCHANGE_ROUNDS = 4


def pruned_segment_topk_mesh(carry, uterms, qimp, live, block_max,
                             qtids, scale_boost, k: int, doc_base,
                             cursor_s, cursor_d, *,
                             axis_name: str = "shard",
                             rounds: int = THETA_EXCHANGE_ROUNDS):
    """One query's block-max sweep over one segment's SHARD-LOCAL block
    partition, inside ``shard_map`` — the pod-slice half of the impact
    lane. Identical contract to :func:`pruned_segment_topk` except:

    * ``doc_base`` is TRACED (global base of this shard's row slice =
      segment base + shard index × local rows; it only enters the
      kernel additively, so tracing it costs nothing);
    * the sweep runs in ``rounds`` chunks of the local descending
      upper-bound order, and at each chunk boundary the shards exchange
      their running k-th score via ``lax.pmax`` over ``axis_name``. A
      block then runs only when its bound can still reach
      ``max(θ_local, θ_external)``.

    Cross-chip pruning stays conservative — hence the gathered per-shard
    top-k lists re-merge to EXACTLY the single-chip result: θ_external
    is some shard's k-th local score at exchange time, every one of that
    shard's local top-k candidates scores ≥ θ_external, so the global
    k-th final score is ≥ θ_external; skipping a block with bound <
    θ_external can therefore never drop a global-top-k doc (a
    global-top-k doc is always in its own shard's local top-k — local
    top-k ⊇ the shard's global-top-k members). The run condition keeps
    ``>=`` so boundary ties survive, exactly as on one chip. Counters
    remain exact per shard (pad sentinels count neither scored nor
    skipped); their ``psum`` differs from the single-chip sweep's split
    only in how MUCH the tighter/staler θ prunes, never in the scores.

    Blocks appended by the S-divisibility pad carry all-zero
    ``block_max`` rows → ``ub_i == 0`` → never run, and ``order``
    chunks shorter than the round width pad with -1 sentinels."""
    np_docs, u = uterms.shape
    n_blocks = block_max.shape[0]
    r = np_docs // n_blocks
    ub_i = block_bounds(block_max, qtids)
    ub_f = ub_i.astype(jnp.float32) * scale_boost
    order = jnp.argsort(-ub_f).astype(jnp.int32)
    n_rounds = max(1, min(int(rounds), n_blocks))       # static
    chunk = -(-n_blocks // n_rounds)
    pad = n_rounds * chunk - n_blocks
    if pad:
        order = jnp.concatenate(
            [order, jnp.full(pad, -1, jnp.int32)])
    order = order.reshape(n_rounds, chunk)

    def make_step(theta_ext):
        def step(c, bi):
            ts, td, n_scored, n_skipped, n_matched = c
            theta = jnp.maximum(ts[k - 1], theta_ext)
            bix = jnp.maximum(bi, 0)          # sentinel-safe index
            run = (bi >= 0) & (ub_i[bix] > 0) & (ub_f[bix] >= theta)

            def hot(c):
                ts, td, n_scored, n_skipped, n_matched = c
                ru = jax.lax.dynamic_slice(uterms, (bix * r, 0), (r, u))
                rq = jax.lax.dynamic_slice(qimp, (bix * r, 0), (r, u))
                rl = jax.lax.dynamic_slice(live, (bix * r,), (r,))
                qsum, anyhit = impact_scores(ru, rq, qtids)
                sf = qsum.astype(jnp.float32) * scale_boost
                docs = bix * r + jnp.arange(r, dtype=jnp.int32) + doc_base
                valid = anyhit & rl & \
                    ((sf < cursor_s) |
                     ((sf == cursor_s) & (docs > cursor_d)))
                sf = jnp.where(valid, sf, NEG_INF)
                docs = jnp.where(valid, docs, -1)
                ts2, td2 = merge_topk_by_doc(ts, td, sf, docs, k)
                return (ts2, td2, n_scored + 1, n_skipped,
                        n_matched + valid.sum(dtype=jnp.int32))

            def cold(c):
                ts, td, n_scored, n_skipped, n_matched = c
                return (ts, td, n_scored,
                        n_skipped + (bi >= 0).astype(jnp.int32),
                        n_matched)

            return jax.lax.cond(run, hot, cold, c), None
        return step

    for ri in range(n_rounds):
        # stale-but-conservative: θ_external was ≤ the global k-th score
        # when exchanged, and the global k-th only grows
        theta_ext = jax.lax.pmax(carry[0][k - 1], axis_name)
        carry, _ = jax.lax.scan(make_step(theta_ext), carry, order[ri])
    return carry


# ---------------------------------------------------------------------------
# device-side rescore stage: impact candidate generation feeds the
# window combine IN-PROGRAM (the planner's impact-rescore arm), so a
# rescore request is one composed dispatch instead of a primary
# dispatch plus a host re-rank pass
# ---------------------------------------------------------------------------

def rescore_gather(uterms, qimp, docs, qtids, doc_base: int):
    """Secondary impact scoring of W candidate GLOBAL doc ids against
    ONE segment's columns: each candidate falling inside this segment
    gathers its impact row and scores against the rescore query's term
    ids (same packed reduction as :func:`impact_scores`).

    → (qsum [W] i32 — zero outside the segment; hit [W] bool — matched
    AND in-segment). Out-of-segment candidates gather a clipped row but
    their result is masked to (0, False), so summing per-segment
    outputs composes the full-reader secondary score exactly (every doc
    lives in exactly one segment)."""
    np_docs = uterms.shape[0]
    local = docs - doc_base
    in_seg = (docs >= 0) & (local >= 0) & (local < np_docs)
    idx = jnp.clip(local, 0, np_docs - 1)
    qsum, anyhit = impact_scores(jnp.take(uterms, idx, axis=0),
                                 jnp.take(qimp, idx, axis=0), qtids)
    return jnp.where(in_seg, qsum, 0), anyhit & in_seg


def rescore_window(scores, docs, sec, sec_hit, window, qw, rw,
                   mode: str):
    """QueryRescorer's window combine + re-sort for ONE query, in
    program — the exact float32 op order of the host oracle
    (phase._apply_rescore): ``prim = score·qw``; matched docs combine
    ``prim`` with ``sec·rw`` per ``mode``; unmatched window docs keep
    ``prim``; ONLY the window re-sorts (score desc, doc asc — the
    host's ``np.lexsort((d, -comb))``) while the tail keeps its
    ORIGINAL primary scores and order.

    scores/docs: [K] primary top-k (score desc, -1-padded); sec: [K]
    f32 secondary scores (already segment-scaled × rescore-query
    boost); window/qw/rw: traced per-query scalars; ``mode`` static —
    the score_mode is part of the compiled-program key."""
    k = scores.shape[0]
    pos = jnp.arange(k, dtype=jnp.int32)
    n_valid = (docs >= 0).sum(dtype=jnp.int32)
    wi = jnp.minimum(window, n_valid)
    in_w = pos < wi
    # both products route through a data-dependent select so no fmul
    # feeds an fadd directly: the CPU backend otherwise contracts
    # mul+add into an fma whose single rounding diverges from the host
    # oracle by 1 ulp. The shield predicates must differ from each
    # other AND from the ``sec_hit`` combine select (same-condition
    # nested selects simplify, re-exposing the contraction edge), and
    # neither false arm may be a constant (constant-arm selects fold
    # into the binop). False arms never reach the output: ``in_w`` rows
    # have valid docs (padding sorts last) and ``comb`` only survives
    # on ``sec_hit & in_w`` rows.
    prim = jnp.where(docs >= 0, scores * qw, scores)
    sec_w = jnp.where(in_w, sec * rw, sec)
    if mode == "total":
        comb = prim + sec_w
    elif mode == "multiply":
        comb = prim * sec_w
    elif mode == "avg":
        comb = (prim + sec_w) / 2.0
    elif mode == "max":
        comb = jnp.maximum(prim, sec_w)
    else:                              # min
        comb = jnp.minimum(prim, sec_w)
    comb = jnp.where(sec_hit, comb, prim)
    new_s = jnp.where(in_w, comb, scores)
    # one lexsort re-sorts the window and keeps the tail fixed: primary
    # key splits window/tail, window items sort by (-score, doc), tail
    # items by original position (positions < 2²⁴ are exact in f32)
    group = (~in_w).astype(jnp.int32)
    mainkey = jnp.where(in_w, -new_s, pos.astype(jnp.float32))
    tiebreak = jnp.where(in_w, docs, 0)
    order = jnp.lexsort((tiebreak, mainkey, group))
    return new_s[order], docs[order]
