"""Impact-ordered scoring and block-max pruning kernels.

The forward kernel (ops/lexical.py) recomputes the full BM25 term
contribution — idf · tf·(k1+1)/(tf+norm) — for every (doc, query term)
pair on every query. The impact lane precomputes that product at
segment-build time into a quantized column (index/segment.py
``ImpactColumn``), so query-time scoring collapses to a dense compare +
integer gather/sum (BM25S, PAPERS.md): no per-doc float math, and the
int sums dequantize with ONE multiply per doc.

``pruned_segment_topk`` adds the asymptotic win: rows are organized in
fixed blocks with a per-(block, term) quantized maximum
(GPUSparse-style dense block table), blocks are swept in descending
upper-bound order under ``lax.scan``, and a block whose bound cannot
beat the running k-th score skips its compute AND its HBM reads through
``lax.cond`` — WAND/block-max, expressed with static shapes so XLA
stays happy. Queries run through ``lax.map`` (not vmap) so the cond
remains a real branch instead of degrading to a select.

Correctness contract (tests/test_impact_index.py): both lanes produce
BIT-IDENTICAL scores (integer sums × the same scale), and pruning is
conservative — a block is skipped only when no query term occurs in it
(block_max carries an occupancy floor of 1 on present cells, so even
fully-zero-quantized terms keep their blocks sweepable) or when its
bound is strictly below the current k-th score (ties kept), so the
pruned top-k equals the unpruned top-k exactly, including the
(score desc, doc asc) tie order of the exact scorer's merge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# module-level import, NEVER inside a traced body: an import executed
# at trace time caches foreign tracers into the imported module's
# globals ("compiled for N+3 inputs" under concurrency — the PR 10 bug
# plane-lint's trace-purity family now guards against)
from elasticsearch_tpu.ops import topk as topk_ops

NEG_INF = jnp.float32(-jnp.inf)
#: doc-id sort key for empty slots: past any real doc id so -inf ties
#: never displace real candidates
_PAD_DOC = jnp.int32(1 << 30)


def impact_scores(uterms, qimp, qtids):
    """Quantized eager scoring of one query against impact columns.

    uterms: [N, U] i32 (-1 pad); qimp: [N, U] uint8/16 quantized
    impacts; qtids: [T] i32 per-segment term ids (-1 absent/pad).

    → (qsum [N] i32 — Σ of matched quantized impacts, exact integer
    arithmetic; anyhit [N] bool — OR-semantics match mask, identical to
    the exact kernel's msm1 mask).

    Score and match count share ONE reduction per term: each entry
    packs ``(q << 8) | 1`` so the sum carries Σq in the high bits and
    the match count in the low byte — halving the [N, U] reduction
    passes vs separate sum + any. Exact because uterms slots are UNIQUE
    per doc (≤ 1 hit per term per doc → count ≤ T ≤ 255) and
    Σq ≤ T·(2¹⁶−1) keeps the shifted sum far inside int32."""
    n = uterms.shape[0]
    enc = (qimp.astype(jnp.int32) << 8) + 1
    acc = jnp.zeros(n, jnp.int32)
    for t in range(qtids.shape[0]):   # T static: unrolled/fused by XLA
        tid = qtids[t]
        hit = (uterms == tid) & (tid >= 0)
        acc = acc + jnp.where(hit, enc, 0).sum(axis=1)
    return acc >> 8, (acc & 0xFF) > 0


def block_bounds(block_max, qtids):
    """Per-block integer upper bounds: Σ_t block_max[:, t] over the
    query terms. ≥ every in-block quantized score (per-term max is an
    upper bound of per-term contribution; sums preserve it — the
    occupancy floor of 1 on present cells only loosens the bound by one
    quantization unit per term). Because absent cells are exactly 0 and
    present cells ≥ 1, ``ub > 0`` ⟺ some query term OCCURS in the
    block — the presence test the pruning sweep keys its skip on."""
    nb = block_max.shape[0]
    ub = jnp.zeros(nb, jnp.int32)
    for t in range(qtids.shape[0]):
        tid = qtids[t]
        col = jnp.take(block_max, jnp.maximum(tid, 0), axis=1)
        ub = ub + jnp.where(tid >= 0, col.astype(jnp.int32), 0)
    return ub


def merge_topk_by_doc(scores_a, docs_a, scores_b, docs_b, k: int):
    """Top-k of the concatenation by (score desc, doc id asc) — the
    exact scorer's merge tie order, made explicit because block-sweep
    candidates arrive out of doc order. Empty slots: (-inf, -1)."""
    s = jnp.concatenate([scores_a, scores_b])
    d = jnp.concatenate([docs_a, docs_b])
    key_d = jnp.where(d >= 0, d, _PAD_DOC)
    by_doc = jnp.argsort(key_d)                       # doc asc
    by_score = jnp.argsort(-s[by_doc])                # stable: doc ties
    sel = by_doc[by_score][:k]
    ts = s[sel]
    return ts, jnp.where(ts > NEG_INF, d[sel], -1)


def eager_segment_topk(uterms, qimp, live, qtids, scale_boost, k: int,
                       doc_base: int, cursor_s, cursor_d):
    """One query × one segment, full (unpruned) impact scoring.

    → (top_scores [k] f32, top_docs [k] i32 segment-LOCAL, count i32).
    ``scale_boost`` = segment dequant scale × query boost (traced);
    ``cursor_s``/``cursor_d`` implement the score-order search_after
    continuation (pass +inf / -1 for no cursor)."""
    n = uterms.shape[0]
    qsum, anyhit = impact_scores(uterms, qimp, qtids)
    sf = qsum.astype(jnp.float32) * scale_boost
    gids = jnp.arange(n, dtype=jnp.int32) + doc_base
    valid = anyhit & live & \
        ((sf < cursor_s) | ((sf == cursor_s) & (gids > cursor_d)))
    count = valid.sum(dtype=jnp.int32)
    ts, td = topk_ops.top_k(sf, valid, min(k, n), 0)
    return ts, td, count


def pruned_segment_topk(carry, uterms, qimp, live, block_max, qtids,
                        scale_boost, k: int, doc_base: int,
                        cursor_s, cursor_d):
    """One query's block-max sweep over one segment, threading the
    running top-k across segments.

    carry = (top_scores [k] f32, top_docs [k] i32 GLOBAL, scored i32,
    skipped i32, matched i32). Blocks are visited in descending
    upper-bound order; a block runs only when its bound can still reach
    the k-th score (``ub >= θ`` — non-strict, so boundary ties survive)
    AND some query term occurs in it at all (``ub_i > 0`` — exact
    PRESENCE, not a score test: block_max stores present cells with a
    floor of 1, so a term whose impacts all quantize to 0 still runs
    its blocks and its score-0 hits match the eager lane's anyhit
    mask). The skipped branch touches none of the block's rows
    (lax.cond): on real hardware that is skipped compute AND skipped
    HBM reads."""
    np_docs, u = uterms.shape
    n_blocks = block_max.shape[0]
    r = np_docs // n_blocks
    ub_i = block_bounds(block_max, qtids)
    ub_f = ub_i.astype(jnp.float32) * scale_boost
    order = jnp.argsort(-ub_f)

    def step(c, bi):
        ts, td, n_scored, n_skipped, n_matched = c
        theta = ts[k - 1]
        run = (ub_i[bi] > 0) & (ub_f[bi] >= theta)

        def hot(c):
            ts, td, n_scored, n_skipped, n_matched = c
            ru = jax.lax.dynamic_slice(uterms, (bi * r, 0), (r, u))
            rq = jax.lax.dynamic_slice(qimp, (bi * r, 0), (r, u))
            rl = jax.lax.dynamic_slice(live, (bi * r,), (r,))
            qsum, anyhit = impact_scores(ru, rq, qtids)
            sf = qsum.astype(jnp.float32) * scale_boost
            docs = bi * r + jnp.arange(r, dtype=jnp.int32) + doc_base
            valid = anyhit & rl & \
                ((sf < cursor_s) | ((sf == cursor_s) & (docs > cursor_d)))
            sf = jnp.where(valid, sf, NEG_INF)
            docs = jnp.where(valid, docs, -1)
            ts2, td2 = merge_topk_by_doc(ts, td, sf, docs, k)
            return (ts2, td2, n_scored + 1, n_skipped,
                    n_matched + valid.sum(dtype=jnp.int32))

        def cold(c):
            ts, td, n_scored, n_skipped, n_matched = c
            return (ts, td, n_scored, n_skipped + 1, n_matched)

        return jax.lax.cond(run, hot, cold, c), None

    carry, _ = jax.lax.scan(step, carry, order)
    return carry


def pruned_carry_init(k: int):
    """Fresh cross-segment carry for :func:`pruned_segment_topk`."""
    return (jnp.full(k, NEG_INF, jnp.float32),
            jnp.full(k, -1, jnp.int32),
            jnp.int32(0), jnp.int32(0), jnp.int32(0))
