"""function_score evaluation over doc-values columns.

Reference: core/index/query/functionscore/* executed via
core/common/lucene/search/function/{FunctionScoreQuery,
FiltersFunctionScoreQuery, FieldValueFactorFunction, ScriptScoreFunction}
(BASELINE.md config 3). Each function maps a doc-values column to a per-doc
factor; score_mode combines multiple functions, boost_mode combines with the
query score — all dense elementwise ops fused into the scoring program.
"""

from __future__ import annotations

import jax.numpy as jnp

from elasticsearch_tpu.utils.hashing import murmur3_hash32


def field_value_factor(values, exists, factor: float = 1.0,
                       modifier: str = "none", missing: float | None = None):
    """FieldValueFactorFunction.java: modifier(factor * value)."""
    v = jnp.where(exists, values, missing if missing is not None else 0.0)
    if missing is None:
        # reference throws on missing w/o default; we score those docs 1.0
        # only if exists handled upstream — keep 0-safe here
        pass
    v = v.astype(jnp.float32) * factor
    if modifier == "none":
        out = v
    elif modifier == "log":
        out = jnp.log10(v)
    elif modifier == "log1p":
        out = jnp.log10(v + 1.0)
    elif modifier == "log2p":
        out = jnp.log10(v + 2.0)
    elif modifier == "ln":
        out = jnp.log(v)
    elif modifier == "ln1p":
        out = jnp.log1p(v)
    elif modifier == "ln2p":
        out = jnp.log(v + 2.0)
    elif modifier == "square":
        out = v * v
    elif modifier == "sqrt":
        out = jnp.sqrt(v)
    elif modifier == "reciprocal":
        out = 1.0 / v
    else:
        raise ValueError(f"unknown field_value_factor modifier [{modifier}]")
    return out


def decay(values, exists, origin: float, scale: float, offset: float,
          decay_value: float, kind: str):
    """gauss/exp/linear decay (DecayFunctionParser.java). All args in the
    value's native units (numbers, millis for dates, meters for geo)."""
    dist = jnp.maximum(jnp.abs(values - origin) - offset, 0.0)
    if kind == "gauss":
        sigma2 = -(scale ** 2) / (2.0 * jnp.log(decay_value))
        out = jnp.exp(-(dist ** 2) / (2.0 * sigma2))
    elif kind == "exp":
        lam = jnp.log(decay_value) / scale
        out = jnp.exp(lam * dist)
    elif kind == "linear":
        s = scale / (1.0 - decay_value)
        out = jnp.maximum((s - dist) / s, 0.0)
    else:
        raise ValueError(f"unknown decay function [{kind}]")
    return jnp.where(exists, out.astype(jnp.float32), 1.0)


def random_score(n: int, seed: int, doc_base: int = 0):
    """RandomScoreFunction: deterministic per (seed, doc id) — uses the same
    murmur-style mixing idea, vectorized."""
    ids = jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(doc_base)
    h = ids * jnp.uint32(0xCC9E2D51) + jnp.uint32(murmur3_hash32(str(seed)) & 0xFFFFFFFF)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    return (h.astype(jnp.float32) / jnp.float32(2**32))


def weight_factor(n: int, weight: float):
    return jnp.full(n, weight, dtype=jnp.float32)


def combine_functions(factors: list, masks: list, score_mode: str,
                      weights: list | None = None):
    """score_mode over per-function factors (function filters pre-applied
    as masks). A doc matched by NO function keeps the combined factor at
    1.0 in EVERY mode — FiltersFunctionScoreQuery.innerScore initializes
    factor = 1.0 and its per-mode guards (±inf for max/min, weightSum ==
    0 for sum/avg) leave it untouched when nothing matched. `weights`
    (per-function scalars, default 1) feed avg's weighted denominator
    (reference: weightSum accumulates WeightFactorFunction weights)."""
    if not factors:
        return None
    if score_mode == "first":
        # first MATCHING function wins (not the first listed one)
        out = jnp.ones_like(factors[0])
        chosen = jnp.zeros(factors[0].shape, bool)
        for f, m in zip(factors, masks):
            take = m & ~chosen
            out = jnp.where(take, f, out)
            chosen = chosen | m
        return out
    if score_mode == "multiply":
        out = None
        for f, m in zip(factors, masks):
            f = jnp.where(m, f, 1.0)
            out = f if out is None else out * f
        return out
    if score_mode in ("sum", "avg"):
        tot, wsum = None, None
        ws = weights if weights is not None else [1.0] * len(factors)
        for f, m, w in zip(factors, masks, ws):
            f = jnp.where(m, f, 0.0)
            c = jnp.where(m, w, 0.0).astype(jnp.float32)
            tot = f if tot is None else tot + f
            wsum = c if wsum is None else wsum + c
        out = tot if score_mode == "sum" else tot / jnp.maximum(wsum, 1e-9)
        return jnp.where(wsum > 0, out, 1.0)
    if score_mode in ("max", "min"):
        red = jnp.maximum if score_mode == "max" else jnp.minimum
        out, any_m = None, None
        for f, m in zip(factors, masks):
            fill = -jnp.inf if score_mode == "max" else jnp.inf
            f = jnp.where(m, f, fill)
            out = f if out is None else red(out, f)
            any_m = m if any_m is None else (any_m | m)
        # fall back to 1.0 only where NO function matched — a matched
        # function legitimately producing ±inf must keep it (the
        # reference's guard compares against the sentinel it seeded,
        # not against infiniteness of the result)
        return jnp.where(any_m, out, 1.0)
    raise ValueError(f"unknown score_mode [{score_mode}]")


def apply_boost_mode(query_scores, factor, boost_mode: str, max_boost: float = None):
    """boost_mode combines the query score with the function factor
    (FunctionScoreQuery.java)."""
    if max_boost is not None:
        factor = jnp.minimum(factor, max_boost)
    if boost_mode == "multiply":
        return query_scores * factor
    if boost_mode == "replace":
        return factor
    if boost_mode == "sum":
        return query_scores + factor
    if boost_mode == "avg":
        return (query_scores + factor) / 2.0
    if boost_mode == "max":
        return jnp.maximum(query_scores, factor)
    if boost_mode == "min":
        return jnp.minimum(query_scores, factor)
    raise ValueError(f"unknown boost_mode [{boost_mode}]")
