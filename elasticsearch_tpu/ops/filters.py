"""Structured filters over doc-values columns.

term/terms/range/exists/prefix over keyword ordinals and numeric columns —
the equivalent of Lucene TermQuery/TermRangeQuery/NumericRangeQuery over
doc values (reference query parsers in core/index/query/). Keyword vocab is
sorted at segment build, so ordinal comparisons implement lexical ranges and
prefix matching becomes an ordinal interval — all dense VPU compares.
"""

from __future__ import annotations

import jax.numpy as jnp


def keyword_term(ords, qord):
    """ords: [N, K] int32 (-1 pad); qord scalar int32 (-1 = absent)."""
    return ((ords == qord) & (qord >= 0)).any(axis=1)


def keyword_terms(ords, qords):
    """Any-of-set membership. qords: [M] int32 (-1 pads)."""
    hit = (ords[:, :, None] == qords[None, None, :]) & (qords[None, None, :] >= 0)
    return hit.any(axis=(1, 2))


def keyword_ord_range(ords, lo: int, hi: int):
    """Ordinal interval [lo, hi) — backs keyword range & prefix queries.
    Host computes lo/hi by binary search over the sorted vocab."""
    valid = ords >= 0
    return (valid & (ords >= lo) & (ords < hi)).any(axis=1)


def _dd_ge(hi, lo, qhi, qlo):
    """(hi, lo) double-double >= (qhi, qlo), exact f64 ordering in f32 ops."""
    return (hi > qhi) | ((hi == qhi) & (lo >= qlo))


def _dd_le(hi, lo, qhi, qlo):
    return (hi < qhi) | ((hi == qhi) & (lo <= qlo))


def _dd_gt(hi, lo, qhi, qlo):
    return (hi > qhi) | ((hi == qhi) & (lo > qlo))


def _dd_lt(hi, lo, qhi, qlo):
    return (hi < qhi) | ((hi == qhi) & (lo < qlo))


def numeric_range(hi, lo, exists, gte_hi, gte_lo, lte_hi, lte_lo,
                  lo_strict=None, hi_strict=None):
    """Exact numeric/date range over the double-double column. Open ends use
    ∓inf for (gte_hi, lte_hi) with 0 lo parts. Exclusive bounds pass
    lo_strict/hi_strict as traced 0/1 scalars — strictness must ride the
    comparison itself, NOT a nextafter-bumped bound: the f64 neighbor of a
    small value (e.g. nextafter(0) = 5e-324) underflows the f32 dd split
    back to the original value, silently turning gt/lt into gte/lte."""
    ge = _dd_ge(hi, lo, gte_hi, gte_lo)
    if lo_strict is not None:
        ge = jnp.where(lo_strict > 0, _dd_gt(hi, lo, gte_hi, gte_lo), ge)
    le = _dd_le(hi, lo, lte_hi, lte_lo)
    if hi_strict is not None:
        le = jnp.where(hi_strict > 0, _dd_lt(hi, lo, lte_hi, lte_lo), le)
    return exists & ge & le


def numeric_term(hi, lo, exists, qhi, qlo):
    return exists & (hi == qhi) & (lo == qlo)


def field_exists(exists):
    return exists


def text_field_exists(doc_len):
    return doc_len > 0


def geo_distance(lat, lon, exists, qlat, qlon, radius_m):
    """Haversine distance filter (reference: GeoDistanceQueryParser)."""
    r = 6371008.8  # mean earth radius, meters
    p1, p2 = jnp.radians(lat), jnp.radians(qlat)
    dphi = jnp.radians(lat - qlat)
    dlmb = jnp.radians(lon - qlon)
    a = jnp.sin(dphi / 2) ** 2 + jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlmb / 2) ** 2
    d = 2 * r * jnp.arcsin(jnp.sqrt(a))
    return exists & (d <= radius_m)


def geo_bounding_box(lat, lon, exists, top, left, bottom, right):
    in_lat = (lat <= top) & (lat >= bottom)
    in_lon = jnp.where(left <= right, (lon >= left) & (lon <= right),
                       (lon >= left) | (lon <= right))  # dateline crossing
    return exists & in_lat & in_lon


def geo_distance_range(lat, lon, exists, qlat, qlon,
                       gte_m, gt_m, lte_m, lt_m):
    """Annulus filter (reference: GeoDistanceRangeQueryParser): bound
    values < 0 mean "unbounded on this side" (host encodes None so)."""
    r = 6371008.8
    p1, p2 = jnp.radians(lat), jnp.radians(qlat)
    dphi = jnp.radians(lat - qlat)
    dlmb = jnp.radians(lon - qlon)
    a = jnp.sin(dphi / 2) ** 2 + \
        jnp.cos(p1) * jnp.cos(p2) * jnp.sin(dlmb / 2) ** 2
    d = 2 * r * jnp.arcsin(jnp.sqrt(a))
    ok = exists
    ok &= (gte_m < 0) | (d >= gte_m)
    ok &= (gt_m < 0) | (d > gt_m)
    ok &= (lte_m < 0) | (d <= lte_m)
    ok &= (lt_m < 0) | (d < lt_m)
    return ok


def geo_polygon(lat, lon, exists, vlats, vlons):
    """Even-odd ray-casting point-in-polygon (reference:
    GeoPolygonQueryParser → GeoPolygonQuery). vlats/vlons: [V] f32 vertex
    ring (closed implicitly; the shared kernel wants an explicit closing
    vertex)."""
    from elasticsearch_tpu.ops.geoshape import _points_in_query_shape
    qlats = jnp.concatenate([vlats, vlats[:1]])
    qlons = jnp.concatenate([vlons, vlons[:1]])
    qrid = jnp.zeros(qlats.shape[0], jnp.int32)
    qarea = jnp.ones(qlats.shape[0], bool)
    return exists & _points_in_query_shape(lat, lon, qlats, qlons,
                                           qrid, qarea)
