"""Masked many-query match reduction — the percolation kernel.

Percolation inverts the search workload: B registered queries score ONE
probe document (a one-doc segment padded to the row bucket). Each vmap
lane produces per-row (scores, mask); what the caller needs per QUERY is
just (matched?, score-of-the-probe-doc). Reducing that inside the fused
program keeps the device→host fetch at O(B) scalars instead of O(B·Np)
row arrays — on a tunneled interconnect the fetch round trip dominates,
so the result of a whole percolate rides back as one small packed array
(the same single-fetch discipline as topk.pack_batch_result_body).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def match_reduce_body(scores, mask):
    """[..., Np] (scores f32, mask bool) → (matched bool, best f32) with
    the trailing row axis reduced: matched = any live row matches, best =
    the max matching score (0.0 when nothing matched — percolate scores
    are non-negative BM25-family sums, and the reference reports 0 for
    no-score modes). Runs under jit/vmap; the mask must already be
    live-masked so padding rows can never match."""
    matched = jnp.any(mask, axis=-1)
    best = jnp.max(jnp.where(mask, scores, -jnp.inf), axis=-1)
    best = jnp.where(matched, best, jnp.float32(0.0))
    return matched, best.astype(jnp.float32)


def pack_match_result_body(matched, best):
    """[B] matched bool + [B] best f32 → ONE [B, 2] f32 array (column 0:
    0/1 match flag, column 1: score) so a percolate lane's whole result
    crosses the link in a single fetch."""
    return jnp.stack([matched.astype(jnp.float32), best], axis=-1)


def unpack_match_result(packed: np.ndarray, b: int):
    """Host side of pack_match_result_body: → (matched [b] bool,
    scores [b] f32), dropping the pow2 batch padding."""
    arr = np.asarray(packed)
    return arr[:b, 0] > 0.5, arr[:b, 1].astype(np.float32)
