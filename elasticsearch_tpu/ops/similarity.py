"""Similarity: BM25 (default) and classic TF/IDF.

Reference: the similarity module (core/index/similarity/SimilarityModule.java
— BM25/default/DFR/IB/LM*) with Lucene 5.4's BM25Similarity semantics:

    idf(t)        = ln(1 + (docCount - df + 0.5) / (df + 0.5))
    tfNorm(tf, d) = tf * (k1 + 1) / (tf + k1 * (1 - b + b * dl/avgdl))
    score(q, d)   = Σ_t idf(t) * tfNorm(tf_t,d)

idf is computed host-side from df aggregated across segments (per shard, the
Lucene default) or across shards via psum (the DFS_QUERY_THEN_FETCH mode,
core/search/dfs/DfsPhase.java:45).
"""

from __future__ import annotations

import math
from dataclasses import dataclass


@dataclass(frozen=True)
class BM25Params:
    k1: float = 1.2
    b: float = 0.75


def idf(df: float, doc_count: float) -> float:
    """Lucene BM25 idf. Accepts scalars; host-side (term stats are host data)."""
    return math.log(1.0 + (doc_count - df + 0.5) / (df + 0.5))


def classic_idf(df: float, doc_count: float) -> float:
    """Lucene ClassicSimilarity (TF/IDF): 1 + ln(docCount / (df + 1))."""
    return 1.0 + math.log(doc_count / (df + 1.0))
