"""geo_shape relation kernels (ref: core/index/query/GeoShapeQueryParser
.java; the reference indexes shapes into a geohash prefix tree and runs
Lucene spatial queries — here shapes are doc-value vertex rings and the
four relations are exact dense polygon tests, looped over query edges so
intermediates stay [N, V]).

Doc shapes: ``lats``/``lons`` [N, V] f32 closed rings (vertex nv == vertex
0), ``nv`` [N] i32 edge counts, ``exists`` [N] bool. Query shape: closed
ring constants [E+1]. All tests treat boundary contact as intersection
(inclusive orientation ≤ 0), matching the reference's default
``intersects`` looseness at cell resolution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _orient(ax, ay, bx, by, cx, cy):
    return (bx - ax) * (cy - ay) - (by - ay) * (cx - ax)


def _doc_edges(dlats, dlons, dnv):
    a_lat, a_lon = dlats[:, :-1], dlons[:, :-1]
    b_lat, b_lon = dlats[:, 1:], dlons[:, 1:]
    valid = jnp.arange(dlats.shape[1] - 1)[None, :] < dnv[:, None]
    return a_lat, a_lon, b_lat, b_lon, valid


def _edge_cross_any(dlats, dlons, dnv, qlats, qlons):
    """[N] — any doc edge intersects any query edge (segment–segment
    orientation test, inclusive of collinear touch)."""
    a_lat, a_lon, b_lat, b_lon, valid = _doc_edges(dlats, dlons, dnv)
    e = qlats.shape[0] - 1

    def body(i, acc):
        c_lat, c_lon = qlats[i], qlons[i]
        d_lat, d_lon = qlats[i + 1], qlons[i + 1]
        o1 = _orient(a_lon, a_lat, b_lon, b_lat, c_lon, c_lat)
        o2 = _orient(a_lon, a_lat, b_lon, b_lat, d_lon, d_lat)
        o3 = _orient(c_lon, c_lat, d_lon, d_lat, a_lon, a_lat)
        o4 = _orient(c_lon, c_lat, d_lon, d_lat, b_lon, b_lat)
        hit = (o1 * o2 <= 0) & (o3 * o4 <= 0) & valid
        return acc | hit.any(axis=1)

    return jax.lax.fori_loop(0, e, body,
                             jnp.zeros(dlats.shape[0], bool))


def _points_in_query_ring(plats, plons, qlats, qlons):
    """Even-odd ray cast of arbitrary-shape point arrays against the
    query ring → bool array of plats' shape."""
    e = qlats.shape[0] - 1

    def body(i, parity):
        yi, xi = qlats[i], qlons[i]
        yj, xj = qlats[i + 1], qlons[i + 1]
        crosses = (yi > plats) != (yj > plats)
        xcross = (xj - xi) * (plats - yi) / jnp.where(
            yj - yi == 0, 1e-30, yj - yi) + xi
        return parity ^ (crosses & (plons < xcross))

    return jax.lax.fori_loop(0, e, body, jnp.zeros(plats.shape, bool))


def _query_point_in_doc_rings(qlat, qlon, dlats, dlons, dnv):
    """[N] — the query ring's first vertex inside each doc's ring."""
    a_lat, a_lon, b_lat, b_lon, valid = _doc_edges(dlats, dlons, dnv)
    crosses = ((a_lat > qlat) != (b_lat > qlat)) & valid
    xcross = (b_lon - a_lon) * (qlat - a_lat) / jnp.where(
        b_lat - a_lat == 0, 1e-30, b_lat - a_lat) + a_lon
    return (crosses & (qlon < xcross)).sum(axis=1) % 2 == 1


def shape_relation(dlats, dlons, dnv, exists, qlats, qlons,
                   relation: str):
    """→ [N] bool mask for intersects / disjoint / within / contains."""
    cross = _edge_cross_any(dlats, dlons, dnv, qlats, qlons)
    doc0_in_q = _points_in_query_ring(dlats[:, 0], dlons[:, 0],
                                      qlats, qlons)
    q0_in_doc = _query_point_in_doc_rings(qlats[0], qlons[0],
                                          dlats, dlons, dnv)
    inter = cross | doc0_in_q | q0_in_doc
    if relation == "intersects":
        return exists & inter
    if relation == "disjoint":
        return exists & ~inter
    if relation == "within":
        # every doc vertex inside the query ring, no boundary crossing
        vparity = _points_in_query_ring(dlats, dlons, qlats, qlons)
        vvalid = jnp.arange(dlats.shape[1])[None, :] <= dnv[:, None]
        all_in = jnp.where(vvalid, vparity, True).all(axis=1)
        return exists & all_in & ~cross
    if relation == "contains":
        # every query vertex inside the doc ring, no boundary crossing
        e = qlats.shape[0] - 1

        def body(i, acc):
            return acc & _query_point_in_doc_rings(
                qlats[i], qlons[i], dlats, dlons, dnv)
        all_in = jax.lax.fori_loop(0, e, body,
                                   jnp.ones(dlats.shape[0], bool))
        return exists & all_in & ~cross
    raise ValueError(f"unknown geo_shape relation [{relation}]")
