"""geo_shape relation kernels (ref: core/index/query/GeoShapeQueryParser
.java; the reference indexes shapes into a geohash prefix tree and runs
Lucene spatial queries — here shapes are doc-value MULTI-RING vertex
soups and the four relations are exact dense tests, looped over query
edges so intermediates stay [N, V]).

Doc shapes: ``lats``/``lons`` [N, V] f32 concatenated rings, ``rid``
[N, V] i32 ring ids (edges exist only between same-rid neighbours; -1 =
pad), ``area`` [N, V] bool (ring encloses area — line runs do not),
``nv`` [N] i32 edge slots, ``exists`` [N] bool. Query shape: constant
arrays of the same layout from utils/geoshape.parse_shape_rings.

Inside-ness is GLOBAL EVEN-ODD parity over area-ring edges: polygon
holes flip parity back out, multipolygon members flip it in — so
polygon-with-holes and multi-geometries need no decomposition
(PolygonBuilder/MultiPolygonBuilder semantics). All edge tests treat
boundary contact as intersection (inclusive orientation ≤ 0), matching
the reference's default ``intersects`` looseness at cell resolution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _orient(ax, ay, bx, by, cx, cy):
    return (bx - ax) * (cy - ay) - (by - ay) * (cx - ax)


def _doc_edges(dlats, dlons, dnv, drid):
    a_lat, a_lon = dlats[:, :-1], dlons[:, :-1]
    b_lat, b_lon = dlats[:, 1:], dlons[:, 1:]
    valid = (jnp.arange(dlats.shape[1] - 1)[None, :] < dnv[:, None]) & \
        (drid[:, :-1] == drid[:, 1:]) & (drid[:, :-1] >= 0)
    return a_lat, a_lon, b_lat, b_lon, valid


def _qedge_valid(qrid, i):
    return qrid[i] == qrid[i + 1]


def _edge_cross_any(dlats, dlons, dnv, drid, qlats, qlons, qrid):
    """[N] — any doc edge intersects any query edge (segment–segment
    orientation test, inclusive of collinear touch)."""
    a_lat, a_lon, b_lat, b_lon, valid = _doc_edges(dlats, dlons, dnv,
                                                   drid)
    e = qlats.shape[0] - 1

    def body(i, acc):
        c_lat, c_lon = qlats[i], qlons[i]
        d_lat, d_lon = qlats[i + 1], qlons[i + 1]
        o1 = _orient(a_lon, a_lat, b_lon, b_lat, c_lon, c_lat)
        o2 = _orient(a_lon, a_lat, b_lon, b_lat, d_lon, d_lat)
        o3 = _orient(c_lon, c_lat, d_lon, d_lat, a_lon, a_lat)
        o4 = _orient(c_lon, c_lat, d_lon, d_lat, b_lon, b_lat)
        hit = (o1 * o2 <= 0) & (o3 * o4 <= 0)
        # all four orientations zero = collinear (incl. any degenerate
        # point edge on the other edge's LINE): the orientation test is
        # vacuous there — require 1-D bounding-interval overlap on both
        # axes or distant collinear segments false-positive
        collinear = (o1 == 0) & (o2 == 0) & (o3 == 0) & (o4 == 0)
        q_lat_lo, q_lat_hi = jnp.minimum(c_lat, d_lat), \
            jnp.maximum(c_lat, d_lat)
        q_lon_lo, q_lon_hi = jnp.minimum(c_lon, d_lon), \
            jnp.maximum(c_lon, d_lon)
        bbox = (jnp.minimum(a_lat, b_lat) <= q_lat_hi) & \
            (jnp.maximum(a_lat, b_lat) >= q_lat_lo) & \
            (jnp.minimum(a_lon, b_lon) <= q_lon_hi) & \
            (jnp.maximum(a_lon, b_lon) >= q_lon_lo)
        hit = hit & jnp.where(collinear, bbox, True) & valid & \
            _qedge_valid(qrid, i)
        return acc | hit.any(axis=1)

    return jax.lax.fori_loop(0, e, body,
                             jnp.zeros(dlats.shape[0], bool))


def _points_in_query_shape(plats, plons, qlats, qlons, qrid, qarea):
    """Global even-odd ray cast of point arrays against the query's
    AREA rings → bool array of plats' shape."""
    e = qlats.shape[0] - 1

    def body(i, parity):
        yi, xi = qlats[i], qlons[i]
        yj, xj = qlats[i + 1], qlons[i + 1]
        crosses = (yi > plats) != (yj > plats)
        xcross = (xj - xi) * (plats - yi) / jnp.where(
            yj - yi == 0, 1e-30, yj - yi) + xi
        gate = _qedge_valid(qrid, i) & qarea[i]
        return parity ^ (crosses & (plons < xcross) & gate)

    return jax.lax.fori_loop(0, e, body, jnp.zeros(plats.shape, bool))


def _query_point_in_doc_shapes(qlat, qlon, dlats, dlons, dnv, drid,
                               darea):
    """[N] — one query vertex inside each doc's area rings (even-odd)."""
    a_lat, a_lon, b_lat, b_lon, valid = _doc_edges(dlats, dlons, dnv,
                                                   drid)
    valid = valid & darea[:, :-1]
    crosses = ((a_lat > qlat) != (b_lat > qlat)) & valid
    xcross = (b_lon - a_lon) * (qlat - a_lat) / jnp.where(
        b_lat - a_lat == 0, 1e-30, b_lat - a_lat) + a_lon
    return (crosses & (qlon < xcross)).sum(axis=1) % 2 == 1


def _ring_starts_np(qrid):
    """Host-side: index of each ring's first vertex (qrid is a host
    numpy constant at trace time)."""
    import numpy as np
    qrid = np.asarray(qrid)
    return [int(i) for i in range(len(qrid))
            if i == 0 or qrid[i] != qrid[i - 1]]


def shape_relation(dlats, dlons, dnv, exists, drid, darea,
                   qlats, qlons, qrid_np, qarea_np, relation: str):
    """→ [N] bool mask for intersects / disjoint / within / contains.

    ``qrid_np``/``qarea_np`` are HOST numpy constants (ring structure is
    static per query); the vertex coordinates ride the const table."""
    qrid = jnp.asarray(qrid_np)
    qarea = jnp.asarray(qarea_np)
    cross = _edge_cross_any(dlats, dlons, dnv, drid, qlats, qlons, qrid)
    # one representative vertex PER doc ring inside the query (a doc
    # member ring wholly inside the query intersects it even when the
    # doc's first ring does not)
    vparity_all = _points_in_query_shape(dlats, dlons, qlats, qlons,
                                         qrid, qarea)
    ring_start = (drid >= 0) & jnp.concatenate(
        [jnp.ones((dlats.shape[0], 1), bool),
         drid[:, 1:] != drid[:, :-1]], axis=1)
    doc0_in_q = (vparity_all & ring_start).any(axis=1)
    # one representative vertex PER query ring inside the doc (a
    # multipolygon member or hole wholly inside the doc intersects it
    # even when the first ring does not)
    q_in_doc = jnp.zeros(dlats.shape[0], bool)
    for start in _ring_starts_np(qrid_np):
        q_in_doc = q_in_doc | _query_point_in_doc_shapes(
            qlats[start], qlons[start], dlats, dlons, dnv, drid, darea)
    inter = cross | doc0_in_q | q_in_doc
    if relation == "intersects":
        return exists & inter
    if relation == "disjoint":
        return exists & ~inter
    if relation == "within":
        # every doc vertex inside the query shape, no boundary crossing
        all_in = jnp.where(drid >= 0, vparity_all, True).all(axis=1)
        return exists & all_in & ~cross
    if relation == "contains":
        # every query vertex inside the doc shape, no boundary crossing
        e = qlats.shape[0] - 1

        def body(i, acc):
            return acc & _query_point_in_doc_shapes(
                qlats[i], qlons[i], dlats, dlons, dnv, drid, darea)
        all_in = jax.lax.fori_loop(0, e, body,
                                   jnp.ones(dlats.shape[0], bool))
        return exists & all_in & ~cross
    raise ValueError(f"unknown geo_shape relation [{relation}]")
