"""Phrase matching over the position-indexed token matrix.

Lucene's ExactPhraseScorer walks position postings of every phrase term in
lockstep (core/index/query/MatchQueryParser.java → Lucene PhraseQuery). Here
the token matrix is position-indexed (``tokens[doc, p]`` = term id at
position ``p``), so an exact-phrase occurrence at start position ``p`` is

    AND_k  tokens[:, p + delta_k] == qtid_k

— a stack of shifted dense compares, vectorized over all docs and all start
positions at once. Query-side position gaps (stopwords removed by the
analyzer) are honored via ``deltas``, matching ES match_phrase semantics.
"""

from __future__ import annotations

import jax.numpy as jnp


def _shift_left(tokens, d: int, fill: int = -(2**31) + 1):
    """tokens[:, p] → tokens[:, p+d] with out-of-range = fill (matches no tid)."""
    if d == 0:
        return tokens
    return jnp.pad(tokens[:, d:], ((0, 0), (0, d)), constant_values=fill)


def phrase_freq(tokens, qtids: list, deltas: list[int]):
    """Phrase frequency per doc.

    Args:
      tokens: [N, L] int32 position-indexed term ids (-1 holes)
      qtids:  list of T scalar int32 per-segment term ids (device scalars;
              -1 = term absent from segment → freq 0 everywhere)
      deltas: list of T static python ints — query token position offsets
              from the first query token (e.g. [0, 1] for adjacent terms,
              [0, 2] when a stopword was removed between them)

    Returns:
      freq: [N] f32 — number of phrase occurrences per doc.
    """
    window = None
    for tid, d in zip(qtids, deltas):
        hit = (_shift_left(tokens, d) == tid) & (tid >= 0)   # [N, L]
        window = hit if window is None else (window & hit)
    return window.sum(axis=1).astype(jnp.float32)


def freq_score(freq, doc_len, sum_idf, k1, b, avgdl):
    """BM25 over a positional frequency (tf = freq, idf = Σ idf of the
    participating terms — Lucene PhraseWeight/SpanWeight combined stats).

    Returns (scores[N] f32, mask[N] bool)."""
    norm = k1 * (1.0 - b + b * doc_len.astype(jnp.float32) / avgdl)
    tf_norm = freq * (k1 + 1.0) / (freq + norm)
    mask = freq > 0
    return jnp.where(mask, sum_idf * tf_norm, 0.0), mask


def phrase_score(tokens, doc_len, qtids: list, deltas: list[int],
                 sum_idf, k1, b, avgdl):
    """BM25 phrase scoring: tf = phrase frequency, idf = Σ idf(term)
    (Lucene PhraseWeight builds its stats from all phrase terms).

    Returns (scores[N] f32, mask[N] bool)."""
    freq = phrase_freq(tokens, qtids, deltas)
    return freq_score(freq, doc_len, sum_idf, k1, b, avgdl)


_INF_SLOP = jnp.float32(1e9)


def _sloppy_displacement(tokens, qtids: list, deltas: list[int], slop: int):
    """→ [N, L] total displacement of the best in-order match anchored at
    each start position (> slop ⇒ no match there). Matches are ANCHORED at
    the first term's actual position (its shift pinned to 0) so each
    occurrence is counted exactly once; every later term takes its NEAREST
    admissible position (min shift in [0, slop]). Deviations from Lucene,
    documented: out-of-order matches (terms moving backwards) are not
    found, and a phrase repeating one term can map two query terms onto
    one token position."""
    total = None
    for i, (tid, d) in enumerate(zip(qtids, deltas)):
        shifts = (0,) if i == 0 else range(slop + 1)
        best = None
        for s in shifts:
            h = (_shift_left(tokens, d + s) == tid) & (tid >= 0)
            cand = jnp.where(h, jnp.float32(s), _INF_SLOP)
            best = cand if best is None else jnp.minimum(best, cand)
        total = best if total is None else total + best
    return total


def sloppy_phrase_freq(tokens, qtids: list, deltas: list[int], slop: int):
    """Proximity-weighted sloppy phrase frequency — Lucene
    SloppyPhraseScorer semantics for in-order matches: each match at total
    displacement d contributes ``1 / (d + 1)`` to the phrase frequency
    (SloppyPhraseScorer.sloppyFreq: 1/(1+matchLength)).

    Returns freq[N] f32. See :func:`_sloppy_displacement` for anchoring
    semantics and documented deviations.
    """
    total = _sloppy_displacement(tokens, qtids, deltas, slop)
    valid = total <= slop
    return jnp.where(valid, 1.0 / (1.0 + total), 0.0).sum(axis=1)


def sloppy_phrase_count(tokens, qtids: list, deltas: list[int], slop: int):
    """Number of in-order matches within the slop budget (each anchored
    occurrence counts 1, NOT the 1/(1+d) sloppyFreq weight) — span_near's
    frequency semantics (NearSpansOrdered enumerates spans; SpanScorer
    then weighs each by sloppyFreq, which this implementation simplifies
    to plain counting, documented in the span_near resolver).

    Returns freq[N] f32.
    """
    total = _sloppy_displacement(tokens, qtids, deltas, slop)
    return (total <= slop).sum(axis=1).astype(jnp.float32)


def sloppy_phrase_score(tokens, doc_len, qtids: list, deltas: list[int],
                        slop: int, idfs, k1, b, avgdl):
    """BM25 over the proximity-weighted sloppy frequency (tf = sloppyFreq,
    idf = Σ idf of the phrase terms, like PhraseWeight's combined stats).

    Returns (scores[N] f32, mask[N] bool)."""
    freq = sloppy_phrase_freq(tokens, qtids, deltas, slop)
    return freq_score(freq, doc_len, jnp.asarray(idfs).sum(), k1, b, avgdl)


def span_near_freq_unordered(tokens, qtids: list, slop: int):
    """Unordered span-near frequency (Lucene NearSpansUnordered analog): a
    span starts at position ``p`` when EVERY clause term occurs somewhere
    in the window ``tokens[p : p+T+slop]``; runs of overlapping starts
    collapse to their first position so each distinct region counts once.
    Deviations from Lucene, documented: per-span width does not feed a
    sloppyFreq weighting (plain freq scoring), and two clause terms may
    map onto one token occurrence when the phrase repeats a term.

    Returns freq[N] f32.
    """
    window = len(qtids) + slop
    match = None
    for tid in qtids:
        present = None
        for d in range(window):
            h = (_shift_left(tokens, d) == tid) & (tid >= 0)
            present = h if present is None else (present | h)
        match = present if match is None else (match & present)
    prev = jnp.pad(match[:, :-1], ((0, 0), (1, 0)), constant_values=False)
    return (match & ~prev).sum(axis=1).astype(jnp.float32)
