"""Boolean clause combination.

The reference's BooleanScorer2/ConjunctionScorer docid-iterator merging
(exercised by core/index/query/BoolQueryParser.java) becomes pure mask
algebra over dense per-doc vectors — conjunction is ``&``, disjunction score
accumulation is ``+``, and ``minimum_should_match`` is a count threshold.
Scoring semantics match Lucene's BooleanWeight:

* must / should clauses contribute their scores (sum);
* filter / must_not contribute no score;
* a doc matches iff all musts match, no must_not matches, and at least
  ``minimum_should_match`` shoulds match (default 1 if there are shoulds and
  no must/filter, else 0).
"""

from __future__ import annotations

import jax.numpy as jnp


def combine_bool(n: int,
                 must: list, should: list, must_not: list, filters: list,
                 minimum_should_match: int):
    """Combine clause results into (scores[N], mask[N]).

    Each element of must/should is a (scores, mask) pair; must_not/filters
    are masks. All lists are static-length (part of the compiled shape).
    """
    scores = jnp.zeros(n, dtype=jnp.float32)
    mask = jnp.ones(n, dtype=bool)
    for s, m in must:
        scores = scores + jnp.where(m, s, 0.0)
        mask = mask & m
    for m in filters:
        mask = mask & m
    for m in must_not:
        mask = mask & ~m
    if should:
        should_count = jnp.zeros(n, dtype=jnp.int32)
        for s, m in should:
            scores = scores + jnp.where(m, s, 0.0)
            should_count = should_count + m.astype(jnp.int32)
        # applied unconditionally so the threshold can be a traced value
        # (msm == 0 makes the predicate vacuously true)
        mask = mask & (should_count >= minimum_should_match)
    return scores, mask


def constant_score(mask, boost: float):
    """filter wrapped in constant_score → every matching doc scores `boost`
    (reference: ConstantScoreQuery)."""
    return jnp.where(mask, jnp.float32(boost), 0.0), mask
