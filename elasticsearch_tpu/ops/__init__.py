"""TPU query kernels.

Pure ``jnp`` functions over the columnar segment arrays. They are composed
by the query executor (search/execute.py) into ONE traced function per
query-plan shape, so XLA fuses the whole scoring pipeline — leaf scorers,
boolean combination, function_score, top-k — into a single device program
(the analog of Lucene's scorer tree executed in
core/search/query/QueryPhase.java:314, but with no per-doc virtual calls).

Conventions:
* every leaf produces ``(scores[N] f32, mask[N] bool)`` over a segment's
  padded doc axis;
* padded/dead rows are masked by the segment live bitmap at the end;
* term ids are per-segment; ``-1`` means "term absent in this segment"
  (kernels guard against -1 matching the -1 padding in columns).
"""

from elasticsearch_tpu.ops.similarity import BM25Params, idf as bm25_idf
from elasticsearch_tpu.ops import lexical, phrase, boolean, filters, topk, vector
from elasticsearch_tpu.ops import functionscore, aggs_ops

__all__ = [
    "BM25Params", "bm25_idf",
    "lexical", "phrase", "boolean", "filters", "topk", "vector",
    "functionscore", "aggs_ops",
]
