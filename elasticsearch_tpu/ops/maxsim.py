"""Fused MaxSim — late-interaction scoring over multi-vector columns.

ColBERT-style late interaction scores a document by summing, per query
token, the best similarity against any document token:
``score(q, d) = Σ_i max_j  q_i · d_j``. The reference era has nothing
like it; FLASH-MAXSIM (PAPERS.md) shows the accelerator-native shape:
never materialize the full ``[N, Qt, T]`` interaction tensor — sweep
the document-token axis in fixed blocks under ``lax.scan``, carrying
only the running per-(doc, query-token) maximum, so intermediates stay
``[N, Qt, blk]`` instead of ``[N, Qt, T]``.

Inputs come from the ``rank_vectors`` mapping type (index/segment.py
``MultiVectorFieldColumn``): per-doc ``[T, D]`` token matrices padded
to the segment-wide token cap, with ``lens[N]`` marking real rows.
Token vectors are L2-normalized at pack time (device layer), so the
per-token dot IS the cosine similarity. Padded doc tokens are masked
to -inf before the max; padded query tokens contribute zero to the
sum; a doc with zero tokens scores 0 (its ``exists`` is False anyway).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = jnp.float32(-jnp.inf)

#: doc-token block width for the scan accumulation. A power of two so
#: the padded token axis (itself pow2-bucketed) divides exactly.
MAXSIM_BLOCK_T = 16


def maxsim_scores_body(toks, lens, q, qmask, block_t: int = MAXSIM_BLOCK_T):
    """MaxSim of ONE query against every doc of a segment.

    toks: [N, T, D] f32 (row-normalized token matrices, zero padding);
    lens: [N] i32 real token counts; q: [Qt, D] f32 (normalized);
    qmask: [Qt] bool (False = query padding).

    → scores [N] f32. Traceable body — runs eagerly and under jit.
    """
    n, t, d = toks.shape
    blk = min(block_t, t)
    n_blocks = -(-t // blk)
    t_pad = n_blocks * blk
    if t_pad != t:
        toks = jnp.pad(toks, ((0, 0), (0, t_pad - t), (0, 0)))
    # [n_blocks, N, blk, D] so scan walks the leading axis
    blocks = jnp.transpose(
        toks.reshape(n, n_blocks, blk, d), (1, 0, 2, 3))
    pos = jnp.arange(t_pad, dtype=jnp.int32).reshape(n_blocks, blk)

    def step(carry, inp):
        chunk, p = inp                      # [N, blk, D], [blk]
        sim = jnp.einsum("nbd,qd->nqb", chunk, q)
        valid = (p[None, :] < lens[:, None])[:, None, :]
        sim = jnp.where(valid, sim, NEG_INF)
        return jnp.maximum(carry, sim.max(axis=2)), None

    init = jnp.full((n, qmask.shape[0]), NEG_INF, jnp.float32)
    tokmax, _ = jax.lax.scan(step, init, (blocks, pos))
    # docs with zero tokens never beat -inf: contribute 0, not -inf
    tokmax = jnp.where(jnp.isfinite(tokmax), tokmax, 0.0)
    return (tokmax * qmask[None, :].astype(jnp.float32)).sum(axis=1)


def maxsim_scores_batch_body(toks, lens, qs, qmasks,
                             block_t: int = MAXSIM_BLOCK_T):
    """B queries × one segment → [B, N] f32. Natively batched (the
    query axis rides the einsum, not a per-query retrace): the scan
    carry is [B, N, Qt] and intermediates stay [B, N, Qt, blk]."""
    n, t, d = toks.shape
    b, qt, _ = qs.shape
    blk = min(block_t, t)
    n_blocks = -(-t // blk)
    t_pad = n_blocks * blk
    if t_pad != t:
        toks = jnp.pad(toks, ((0, 0), (0, t_pad - t), (0, 0)))
    blocks = jnp.transpose(
        toks.reshape(n, n_blocks, blk, d), (1, 0, 2, 3))
    pos = jnp.arange(t_pad, dtype=jnp.int32).reshape(n_blocks, blk)

    def step(carry, inp):
        chunk, p = inp                  # [N, blk, D], [blk]
        sim = jnp.einsum("ncd,bqd->bnqc", chunk, qs)
        valid = (p[None, :] < lens[:, None])[None, :, None, :]
        sim = jnp.where(valid, sim, NEG_INF)
        return jnp.maximum(carry, sim.max(axis=3)), None

    init = jnp.full((b, n, qt), NEG_INF, jnp.float32)
    tokmax, _ = jax.lax.scan(step, init, (blocks, pos))
    tokmax = jnp.where(jnp.isfinite(tokmax), tokmax, 0.0)
    return (tokmax * qmasks[:, None, :].astype(jnp.float32)).sum(axis=2)


def maxsim_scores_int8_body(qtoks, scale, offset, lens, q, qmask,
                            block_t: int = MAXSIM_BLOCK_T):
    """MaxSim over an int8-quantized token column.

    qtoks: [N, T, D] int8 with ``v ≈ q·scale + offset`` per component
    (per-segment scale/offset snapshot, index/segment.py
    ``quantize_vectors``). The dequantized dot expands to
    ``scale·(qint·q) + offset·Σq`` — one integer-width matmul plus a
    rank-1 correction, so the column stays int8 in HBM (~4× density).
    """
    # dequantized dot: scale·(qint·q) + offset·Σq. The affine correction
    # offset·Σq_i is constant over the DOC-token axis, and scale ≥ 0, so
    # max_j(scale·x_j + c_i) = scale·max_j(x_j) + c_i — the max can run
    # on the integer-valued similarities and correct afterwards.
    qsum = q.sum(axis=1)                    # [Qt]
    n, t, d = qtoks.shape
    blk = min(block_t, t)
    n_blocks = -(-t // blk)
    t_pad = n_blocks * blk
    toks = qtoks.astype(jnp.float32)
    if t_pad != t:
        toks = jnp.pad(toks, ((0, 0), (0, t_pad - t), (0, 0)))
    blocks = jnp.transpose(
        toks.reshape(n, n_blocks, blk, d), (1, 0, 2, 3))
    pos = jnp.arange(t_pad, dtype=jnp.int32).reshape(n_blocks, blk)

    def step(carry, inp):
        chunk, p = inp
        sim = jnp.einsum("nbd,qd->nqb", chunk, q)
        valid = (p[None, :] < lens[:, None])[:, None, :]
        sim = jnp.where(valid, sim, NEG_INF)
        return jnp.maximum(carry, sim.max(axis=2)), None

    init = jnp.full((n, qmask.shape[0]), NEG_INF, jnp.float32)
    intmax, _ = jax.lax.scan(step, init, (blocks, pos))
    tokmax = intmax * scale + offset * qsum[None, :]
    tokmax = jnp.where(jnp.isfinite(intmax), tokmax, 0.0)
    return (tokmax * qmask[None, :].astype(jnp.float32)).sum(axis=1)


def maxsim_scores_int8_batch_body(qtoks, scale, offset, lens, qs, qmasks,
                                  block_t: int = MAXSIM_BLOCK_T):
    """Natively batched int8 MaxSim: integer-valued similarities max
    under the scan, the affine dequant correction (constant over the
    doc-token axis, scale ≥ 0) applied to the per-token maxima."""
    n, t, d = qtoks.shape
    b, qt, _ = qs.shape
    blk = min(block_t, t)
    n_blocks = -(-t // blk)
    t_pad = n_blocks * blk
    toks = qtoks.astype(jnp.float32)
    if t_pad != t:
        toks = jnp.pad(toks, ((0, 0), (0, t_pad - t), (0, 0)))
    blocks = jnp.transpose(
        toks.reshape(n, n_blocks, blk, d), (1, 0, 2, 3))
    pos = jnp.arange(t_pad, dtype=jnp.int32).reshape(n_blocks, blk)

    def step(carry, inp):
        chunk, p = inp
        sim = jnp.einsum("ncd,bqd->bnqc", chunk, qs)
        valid = (p[None, :] < lens[:, None])[None, :, None, :]
        sim = jnp.where(valid, sim, NEG_INF)
        return jnp.maximum(carry, sim.max(axis=3)), None

    init = jnp.full((b, n, qt), NEG_INF, jnp.float32)
    intmax, _ = jax.lax.scan(step, init, (blocks, pos))
    qsums = qs.sum(axis=2)                       # [B, Qt]
    tokmax = intmax * scale + offset * qsums[:, None, :]
    tokmax = jnp.where(jnp.isfinite(intmax), tokmax, 0.0)
    return (tokmax * qmasks[:, None, :].astype(jnp.float32)).sum(axis=2)
