"""Runtime lock-order watchdog: the dynamic half of plane-lint's
lock-discipline rule.

The static rule computes the lock-acquisition graph from ``with <lock>``
statements across the threaded modules (see
:func:`elasticsearch_tpu.analysis.lint.lock_graph_for`). This module
checks the SAME order at runtime: with ``ESTPU_LOCK_WATCHDOG=1``, every
lock the ``elasticsearch_tpu`` package constructs is wrapped, each
thread's acquisition stack is tracked, and acquiring lock B while
holding lock A is a violation when the static graph orders B before A
(edge B→A with no A→B counterpart). The chaos-matrix tier-1 smoke cases
run under :func:`watching`, so an ordering the analyzer believes in but
the cluster does not actually follow — or vice versa — fails the case
instead of deadlocking a production node.

Violations are RECORDED, not raised at the acquisition site (a raise
inside a background replication thread would be swallowed or wedge the
cluster mid-teardown); :func:`watching` re-raises them as
:class:`LockOrderError` when the scenario finishes. Pass ``strict=True``
to raise at the acquisition site instead (useful under a debugger).

Lock identities resolve lazily at first acquisition, to the same dotted
names the static graph uses: ``self._lock`` inside class C of module m →
``m.C._lock``; a module-global ``_cache_lock`` → ``m._cache_lock``.
Locks the resolver cannot name (locals, comprehension temporaries) are
tracked for stack bookkeeping but never flagged.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading

ENV_FLAG = "ESTPU_LOCK_WATCHDOG"

_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

#: the static graph is computed once per process (parsing ~130 files);
#: (edges, ranks) after canonicalization
_graph_cache = None


class LockOrderError(AssertionError):
    """Runtime lock acquisition contradicted the static lock graph."""


def _canon(ident: str) -> str:
    """Normalize a dotted identity so the static graph (relpath-derived)
    and the runtime resolver (__module__-derived) agree regardless of
    the working directory the analyzer ran from."""
    idx = ident.find("elasticsearch_tpu")
    return ident[idx:] if idx > 0 else ident


def static_lock_graph():
    """(edges, ranks) of the package's canonicalized static lock graph,
    computed once per process."""
    global _graph_cache
    if _graph_cache is None:
        from elasticsearch_tpu.analysis.lint import lock_graph_for
        pkg_dir = os.path.dirname(os.path.dirname(__file__))
        raw_edges, raw_ranks = lock_graph_for([pkg_dir])
        edges = {(_canon(a), _canon(b)) for a, b in raw_edges}
        ranks = {_canon(n): r for n, r in raw_ranks.items()}
        _graph_cache = (edges, ranks)
    return _graph_cache


class _WatchedLock:
    """A threading lock that reports its acquisitions to the watchdog.
    Resolution of the dotted identity happens at acquisition time — the
    creating frame knows the module, but only the acquiring frame can
    say which attribute / global the lock was bound to."""

    __slots__ = ("_real", "_wd", "_ident")

    def __init__(self, real, wd):
        self._real = real
        self._wd = wd
        self._ident = None

    # -- identity ----------------------------------------------------------

    def _resolve(self, frame) -> str | None:
        if self._ident is not None:
            return self._ident
        if frame is None:
            return None
        self_obj = frame.f_locals.get("self")
        if self_obj is not None:
            try:
                attrs = vars(self_obj)
            except TypeError:
                attrs = {}
            for attr, value in attrs.items():
                if value is self:
                    cls = type(self_obj)
                    self._ident = _canon(
                        f"{cls.__module__}.{cls.__name__}.{attr}")
                    return self._ident
        g = frame.f_globals
        for name, value in g.items():
            if value is self:
                self._ident = _canon(f"{g.get('__name__', '?')}.{name}")
                return self._ident
        return None

    # -- lock protocol -----------------------------------------------------

    def acquire(self, blocking=True, timeout=-1, _frame=None):
        frame = _frame if _frame is not None else sys._getframe(1)
        ident = self._resolve(frame)
        got = self._real.acquire(blocking, timeout)
        if got:
            self._wd._note_acquire(self, ident, frame)
        return got

    def release(self):
        self._wd._note_release(self)
        self._real.release()

    def __enter__(self):
        self.acquire(_frame=sys._getframe(1))
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked() if hasattr(self._real, "locked") \
            else None

    def __repr__(self):
        return f"<WatchedLock {self._ident or '?'} of {self._real!r}>"


class Watchdog:
    def __init__(self, edges, ranks=None, strict=False):
        self.edges = set(edges)
        self.ranks = dict(ranks or {})
        self.strict = strict
        self.violations: list[str] = []
        self._tls = threading.local()
        self._mu = _ORIG_LOCK()

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _note_acquire(self, lock, ident, frame) -> None:
        stack = self._stack()
        if ident is not None:
            for held_lock, held_ident in stack:
                if held_ident is None or held_ident == ident or \
                        held_lock is lock:
                    continue
                if (ident, held_ident) in self.edges and \
                        (held_ident, ident) not in self.edges:
                    where = f"{frame.f_code.co_filename}:" \
                            f"{frame.f_lineno}" if frame else "?"
                    msg = (f"acquired {ident} while holding {held_ident} "
                           f"at {where}, but the static lock graph "
                           f"orders {ident} BEFORE {held_ident} — "
                           f"potential deadlock against the analyzed "
                           f"order")
                    with self._mu:
                        self.violations.append(msg)
                    if self.strict:
                        raise LockOrderError(msg)
        stack.append((lock, ident))

    def _note_release(self, lock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                del stack[i]
                return

    def check(self) -> None:
        """Raise LockOrderError if any violation was recorded."""
        if self.violations:
            raise LockOrderError(
                f"{len(self.violations)} lock-order violation(s):\n" +
                "\n".join(self.violations))


_active: Watchdog | None = None


def enable(edges=None, ranks=None, strict=False) -> Watchdog:
    """Patch ``threading.Lock`` / ``threading.RLock`` so locks created
    by ``elasticsearch_tpu`` modules from here on are order-checked
    against `edges` (default: the static graph). Idempotent — a second
    enable returns the active watchdog."""
    global _active
    if _active is not None:
        return _active
    if edges is None:
        edges, ranks = static_lock_graph()
    wd = Watchdog(edges, ranks, strict=strict)

    def _factory(real_ctor):
        def make():
            real = real_ctor()
            mod = sys._getframe(1).f_globals.get("__name__", "")
            if not mod.startswith("elasticsearch_tpu"):
                return real
            return _WatchedLock(real, wd)
        return make

    threading.Lock = _factory(_ORIG_LOCK)
    threading.RLock = _factory(_ORIG_RLOCK)
    _active = wd
    return wd


def disable() -> Watchdog | None:
    """Restore the real lock factories → the watchdog that was active
    (its recorded violations survive), or None."""
    global _active
    threading.Lock = _ORIG_LOCK
    threading.RLock = _ORIG_RLOCK
    wd, _active = _active, None
    return wd


def enabled_by_env() -> bool:
    return os.environ.get(ENV_FLAG, "") not in ("", "0", "false")


@contextlib.contextmanager
def watching(strict=False, force=False):
    """Run a block under the watchdog when ``ESTPU_LOCK_WATCHDOG=1``
    (or ``force=True``); on exit, restore the factories and re-raise any
    recorded violation as :class:`LockOrderError`. A no-op yielding None
    when the flag is off — the chaos matrix wraps every case in this."""
    if not (force or enabled_by_env()):
        yield None
        return
    wd = enable(strict=strict)
    try:
        yield wd
    finally:
        disable()
    wd.check()
