"""Analysis: tokenizers, token filters, analyzers, per-index registry.

Mirrors the reference's analysis module (core/index/analysis/AnalysisModule.java:39,
~150 providers bridging Lucene analyzers): named tokenizers + filter chains are
registered globally, and each index can define custom analyzers in its settings
(``analysis.analyzer.<name>.{type,tokenizer,filter}``), resolved by
:class:`AnalysisRegistry`.

This runs host-side at both index time (SegmentBuilder) and query time
(match-query analysis); the produced term streams are what get packed into
the device-resident columnar segments.
"""

from __future__ import annotations

import re
import unicodedata
from dataclasses import dataclass
from typing import Callable, Iterable, Sequence

from elasticsearch_tpu.common.errors import IllegalArgumentError
from elasticsearch_tpu.common.settings import Settings


@dataclass
class Token:
    term: str
    position: int      # token position (phrase queries use this)
    start_offset: int  # char offsets (highlighting uses these)
    end_offset: int


# ---------------------------------------------------------------------------
# Tokenizers
# ---------------------------------------------------------------------------

# Word characters: letters and digits of any script (approximates Lucene's
# StandardTokenizer UAX#29 word-break rules closely enough for parity tests).
# \w includes '_': UAX#29 (Lucene StandardTokenizer) classes underscore as
# ExtendNumLet, which JOINS words — "value1_foo" is ONE token. All-
# underscore matches are dropped below (no word chars → no token).
_STANDARD_RE = re.compile(r"\w+(?:['’]\w+)*", re.UNICODE)
_WHITESPACE_RE = re.compile(r"\S+")
_LETTER_RE = re.compile(r"[^\W\d_]+", re.UNICODE)


def _regex_tokenize(text: str, pattern: re.Pattern) -> list[Token]:
    out = []
    for pos, m in enumerate(pattern.finditer(text)):
        out.append(Token(m.group(0), pos, m.start(), m.end()))
    return out


def standard_tokenizer(text: str) -> list[Token]:
    toks = _regex_tokenize(text, _STANDARD_RE)
    kept = [t for t in toks if t.term.strip("_")]
    # re-number positions after dropping underscore-only matches
    return [Token(t.term, pos, t.start_offset, t.end_offset)
            for pos, t in enumerate(kept)]


def whitespace_tokenizer(text: str) -> list[Token]:
    return _regex_tokenize(text, _WHITESPACE_RE)


def letter_tokenizer(text: str) -> list[Token]:
    return _regex_tokenize(text, _LETTER_RE)


# ---- native acceleration ---------------------------------------------------
# The C tokenizer (native/tokenizer.c) implements the same boundary rules
# over the CPython unicode API (~10x the regex path on the bulk-indexing
# hot loop). Semantics parity is pinned by tests/test_native_tokenizer.py
# against these Python reference implementations, which stay the fallback
# when no toolchain is available.
py_standard_tokenizer = standard_tokenizer
py_whitespace_tokenizer = whitespace_tokenizer
py_letter_tokenizer = letter_tokenizer

try:
    from elasticsearch_tpu.native import load_tokenizer as _load_native
    _native = _load_native()
except Exception:           # noqa: BLE001 — any build/load failure
    _native = None

if _native is not None:
    def _native_tokenizer(mode: int):
        native_tok = _native.tokenize

        def tokenizer(text: str) -> list[Token]:
            return [Token(t, p, a, b)
                    for (t, p, a, b) in native_tok(text, mode, False)]
        return tokenizer

    standard_tokenizer = _native_tokenizer(0)
    whitespace_tokenizer = _native_tokenizer(1)
    letter_tokenizer = _native_tokenizer(2)


def keyword_tokenizer(text: str) -> list[Token]:
    return [Token(text, 0, 0, len(text))] if text else []


def ngram_tokenizer_factory(min_gram: int = 1, max_gram: int = 2) -> "Tokenizer":
    def tok(text: str) -> list[Token]:
        out = []
        pos = 0
        for n in range(min_gram, max_gram + 1):
            for i in range(0, len(text) - n + 1):
                out.append(Token(text[i:i + n], pos, i, i + n))
                pos += 1
        return out
    return tok


Tokenizer = Callable[[str], list[Token]]

TOKENIZERS: dict[str, Tokenizer] = {
    "standard": standard_tokenizer,
    "whitespace": whitespace_tokenizer,
    "letter": letter_tokenizer,
    "keyword": keyword_tokenizer,
    "classic": standard_tokenizer,
}


# ---------------------------------------------------------------------------
# Token filters
# ---------------------------------------------------------------------------

# Lucene's default English stopword set (StandardAnalyzer.STOP_WORDS_SET).
ENGLISH_STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split()
)


def lowercase_filter(tokens: Iterable[Token]) -> list[Token]:
    return [Token(t.term.lower(), t.position, t.start_offset, t.end_offset) for t in tokens]


def uppercase_filter(tokens: Iterable[Token]) -> list[Token]:
    return [Token(t.term.upper(), t.position, t.start_offset, t.end_offset) for t in tokens]


def asciifolding_filter(tokens: Iterable[Token]) -> list[Token]:
    def fold(s: str) -> str:
        return "".join(
            c for c in unicodedata.normalize("NFKD", s) if not unicodedata.combining(c)
        )
    return [Token(fold(t.term), t.position, t.start_offset, t.end_offset) for t in tokens]


def stop_filter_factory(stopwords: frozenset[str] = ENGLISH_STOPWORDS) -> "TokenFilter":
    """Removes stopwords; positions are preserved (position gaps matter for
    phrase queries, matching Lucene StopFilter's enablePositionIncrements)."""
    def f(tokens: Iterable[Token]) -> list[Token]:
        return [t for t in tokens if t.term not in stopwords]
    return f


def length_filter_factory(min_len: int = 0, max_len: int = 255) -> "TokenFilter":
    def f(tokens: Iterable[Token]) -> list[Token]:
        return [t for t in tokens if min_len <= len(t.term) <= max_len]
    return f


def unique_filter(tokens: Iterable[Token]) -> list[Token]:
    seen: set[str] = set()
    out = []
    for t in tokens:
        if t.term not in seen:
            seen.add(t.term)
            out.append(t)
    return out


def shingle_filter_factory(min_size: int = 2, max_size: int = 2,
                           separator: str = " ") -> "TokenFilter":
    def f(tokens: Iterable[Token]) -> list[Token]:
        toks = list(tokens)
        out = list(toks)
        for n in range(min_size, max_size + 1):
            for i in range(len(toks) - n + 1):
                grp = toks[i:i + n]
                out.append(Token(separator.join(t.term for t in grp),
                                 grp[0].position, grp[0].start_offset, grp[-1].end_offset))
        out.sort(key=lambda t: (t.position, t.end_offset))
        return out
    return f


# --- Porter stemmer (Porter 1980; equivalent of Lucene PorterStemFilter) ----

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    """Number of VC sequences."""
    m, prev_cons = 0, True
    for i in range(len(stem)):
        cons = _is_cons(stem, i)
        if prev_cons and not cons:
            pass
        elif not prev_cons and cons:
            m += 1
        prev_cons = cons
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return (len(word) >= 2 and word[-1] == word[-2] and _is_cons(word, len(word) - 1))


def _cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    return (_is_cons(word, len(word) - 3) and not _is_cons(word, len(word) - 2)
            and _is_cons(word, len(word) - 1) and word[-1] not in "wxy")


def porter_stem(word: str) -> str:  # noqa: C901 — the algorithm is one long rule table
    if len(word) <= 2:
        return word
    w = word
    # Step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]
    # Step 1b
    flag = False
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    elif w.endswith("ed"):
        if _has_vowel(w[:-2]):
            w, flag = w[:-2], True
    elif w.endswith("ing"):
        if _has_vowel(w[:-3]):
            w, flag = w[:-3], True
    if flag:
        if w.endswith(("at", "bl", "iz")):
            w += "e"
        elif _ends_double_cons(w) and not w.endswith(("l", "s", "z")):
            w = w[:-1]
        elif _measure(w) == 1 and _cvc(w):
            w += "e"
    # Step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # Step 2
    step2 = [("ational", "ate"), ("tional", "tion"), ("enci", "ence"), ("anci", "ance"),
             ("izer", "ize"), ("bli", "ble"), ("alli", "al"), ("entli", "ent"),
             ("eli", "e"), ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
             ("ator", "ate"), ("alism", "al"), ("iveness", "ive"), ("fulness", "ful"),
             ("ousness", "ous"), ("aliti", "al"), ("iviti", "ive"), ("biliti", "ble"),
             ("logi", "log")]
    for suf, rep in step2:
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # Step 3
    step3 = [("icate", "ic"), ("ative", ""), ("alize", "al"), ("iciti", "ic"),
             ("ical", "ic"), ("ful", ""), ("ness", "")]
    for suf, rep in step3:
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # Step 4
    step4 = ["al", "ance", "ence", "er", "ic", "able", "ible", "ant", "ement",
             "ment", "ent", "ion", "ou", "ism", "ate", "iti", "ous", "ive", "ize"]
    for suf in step4:
        if w.endswith(suf):
            stem = w[:-len(suf)]
            if _measure(stem) > 1:
                if suf == "ion" and not stem.endswith(("s", "t")):
                    break
                w = stem
            break
    # Step 5a
    if w.endswith("e"):
        stem = w[:-1]
        if _measure(stem) > 1 or (_measure(stem) == 1 and not _cvc(stem)):
            w = stem
    # Step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w


def porter_stem_filter(tokens: Iterable[Token]) -> list[Token]:
    return [Token(porter_stem(t.term), t.position, t.start_offset, t.end_offset)
            for t in tokens]


TokenFilter = Callable[[Iterable[Token]], list[Token]]

def trim_filter(tokens: Iterable[Token]) -> list[Token]:
    return [Token(t.term.strip(), t.position, t.start_offset,
                  t.end_offset) for t in tokens]


def reverse_filter(tokens: Iterable[Token]) -> list[Token]:
    return [Token(t.term[::-1], t.position, t.start_offset, t.end_offset)
            for t in tokens]


def truncate_filter_factory(length: int = 10) -> "TokenFilter":
    def f(tokens: Iterable[Token]) -> list[Token]:
        return [Token(t.term[:length], t.position, t.start_offset,
                      t.end_offset) for t in tokens]
    return f


def limit_filter_factory(max_token_count: int = 1) -> "TokenFilter":
    def f(tokens: Iterable[Token]) -> list[Token]:
        return list(tokens)[:max_token_count]
    return f


def decimal_digit_filter(tokens: Iterable[Token]) -> list[Token]:
    """Unicode decimal digits → ASCII 0-9 (DecimalDigitFilter)."""
    import unicodedata

    def fold(s: str) -> str:
        return "".join(str(unicodedata.decimal(c)) if
                       unicodedata.category(c) == "Nd" else c for c in s)
    return [Token(fold(t.term), t.position, t.start_offset, t.end_offset)
            for t in tokens]


def cjk_width_filter(tokens: Iterable[Token]) -> list[Token]:
    """Full-width ASCII / half-width katakana normalization
    (CJKWidthFilter ≈ NFKC on those ranges)."""
    import unicodedata
    return [Token(unicodedata.normalize("NFKC", t.term), t.position,
                  t.start_offset, t.end_offset) for t in tokens]


_ELISION_ARTICLES = frozenset(
    "l m t qu n s j d c jusqu quoiqu lorsqu puisqu".split())


def elision_filter_factory(articles=None) -> "TokenFilter":
    arts = frozenset(a.lower() for a in articles) if articles \
        else _ELISION_ARTICLES

    def f(tokens: Iterable[Token]) -> list[Token]:
        out = []
        for t in tokens:
            term = t.term
            for sep in ("'", "’"):
                head, s, tail = term.partition(sep)
                if s and head.lower() in arts:
                    term = tail
                    break
            out.append(Token(term, t.position, t.start_offset,
                             t.end_offset))
        return out
    return f


def apostrophe_filter(tokens: Iterable[Token]) -> list[Token]:
    """Strip everything after an apostrophe (ApostropheFilter)."""
    return [Token(t.term.partition("'")[0] or t.term, t.position,
                  t.start_offset, t.end_offset) for t in tokens]


def keep_filter_factory(keep_words) -> "TokenFilter":
    kept = frozenset(keep_words)

    def f(tokens: Iterable[Token]) -> list[Token]:
        return [t for t in tokens if t.term in kept]
    return f


def edge_ngram_filter_factory(min_gram: int = 1,
                              max_gram: int = 2) -> "TokenFilter":
    def f(tokens: Iterable[Token]) -> list[Token]:
        out = []
        for t in tokens:
            for n in range(min_gram, min(max_gram, len(t.term)) + 1):
                out.append(Token(t.term[:n], t.position, t.start_offset,
                                 t.end_offset))
        return out
    return f


def ngram_filter_factory(min_gram: int = 1,
                         max_gram: int = 2) -> "TokenFilter":
    def f(tokens: Iterable[Token]) -> list[Token]:
        out = []
        for t in tokens:
            for n in range(min_gram, max_gram + 1):
                for i in range(0, len(t.term) - n + 1):
                    out.append(Token(t.term[i:i + n], t.position,
                                     t.start_offset, t.end_offset))
        return out
    return f


def pattern_replace_filter_factory(pattern: str,
                                   replacement: str = "") -> "TokenFilter":
    rx = re.compile(pattern)

    def f(tokens: Iterable[Token]) -> list[Token]:
        return [Token(rx.sub(replacement, t.term), t.position,
                      t.start_offset, t.end_offset) for t in tokens]
    return f


def synonym_filter_factory(synonyms: list) -> "TokenFilter":
    """Inline synonym list (SynonymTokenFilterFactory), Solr format:
    'a, b => c' maps a and b to c; 'a, b, c' makes the group equivalent
    (every member expands to all members, same position)."""
    expand: dict[str, list[str]] = {}
    for rule in synonyms or []:
        if "=>" in rule:
            lhs, rhs = rule.split("=>", 1)
            targets = [w.strip() for w in rhs.split(",") if w.strip()]
            for src in (w.strip() for w in lhs.split(",")):
                if src:
                    expand[src] = targets
        else:
            group = [w.strip() for w in rule.split(",") if w.strip()]
            for src in group:
                expand[src] = group

    def f(tokens: Iterable[Token]) -> list[Token]:
        # multi-word targets expand to consecutive positions and shift
        # everything after them (a flattened SynonymGraph: "ny => new
        # york" keeps "new york" phrase-matchable)
        out = []
        shift = 0
        for t in tokens:
            base = t.position + shift
            terms = expand.get(t.term)
            if terms is None:
                out.append(Token(t.term, base, t.start_offset,
                                 t.end_offset))
                continue
            width = 1
            seen = set()
            for term in terms:
                if term in seen:
                    continue
                seen.add(term)
                words = term.split()
                for wi, w in enumerate(words):
                    out.append(Token(w, base + wi, t.start_offset,
                                     t.end_offset))
                width = max(width, len(words))
            shift += width - 1
        return out
    return f


_WORD_DELIM_SPLIT = re.compile(
    r"[A-Z]?[a-z]+|[A-Z]+(?![a-z])|\d+")


def word_delimiter_filter_factory(params: dict) -> "TokenFilter":
    """WordDelimiterTokenFilterFactory core behavior: split on case
    transitions / letter-digit boundaries / intra-word punctuation;
    optionally keep the original token."""
    preserve = str(params.get("preserve_original",
                              "false")).lower() in ("true", "1")

    def f(tokens: Iterable[Token]) -> list[Token]:
        out = []
        for t in tokens:
            parts = _WORD_DELIM_SPLIT.findall(t.term)
            if len(parts) <= 1:
                # no split: one token, whether or not preserving (Lucene
                # emits the original exactly once here)
                out.append(Token(parts[0] if parts else t.term,
                                 t.position, t.start_offset,
                                 t.end_offset))
                continue
            if preserve:
                out.append(t)
            for p in parts:
                out.append(Token(p, t.position, t.start_offset,
                                 t.end_offset))
        return out
    return f


def edge_ngram_tokenizer_factory(min_gram: int = 1,
                                 max_gram: int = 2) -> "Tokenizer":
    def tok(text: str) -> list[Token]:
        out = []
        for n in range(min_gram, min(max_gram, len(text)) + 1):
            out.append(Token(text[:n], 0, 0, n))
        return out
    return tok


def pattern_tokenizer_factory(pattern: str = r"\W+",
                              group: int = -1) -> "Tokenizer":
    rx = re.compile(pattern)

    def tok(text: str) -> list[Token]:
        out = []
        if group >= 0:
            for pos, m in enumerate(rx.finditer(text)):
                out.append(Token(m.group(group), pos, m.start(), m.end()))
            return out
        pos = 0
        idx = 0
        for part in rx.split(text):
            if part:
                start = text.index(part, idx)
                out.append(Token(part, pos, start, start + len(part)))
                pos += 1
                idx = start + len(part)
        return out
    return tok


def path_hierarchy_tokenizer_factory(delimiter: str = "/") -> "Tokenizer":
    def tok(text: str) -> list[Token]:
        out = []
        parts = text.split(delimiter)
        acc = ""
        for i, part in enumerate(parts):
            acc = part if i == 0 else acc + delimiter + part
            if acc:
                out.append(Token(acc, 0, 0, len(acc)))
        return out
    return tok


_URL_EMAIL = re.compile(
    r"https?://[^\s]+|[\w.+-]+@[\w-]+\.[\w.-]+|\w+")


def uax_url_email_tokenizer(text: str) -> list[Token]:
    # no case folding here — that is the lowercase filter's job, like
    # Lucene's UAX29URLEmailTokenizer
    return [Token(m.group(0), pos, m.start(), m.end())
            for pos, m in enumerate(_URL_EMAIL.finditer(text))]


TOKEN_FILTERS: dict[str, TokenFilter] = {
    "lowercase": lowercase_filter,
    "uppercase": uppercase_filter,
    "asciifolding": asciifolding_filter,
    "stop": stop_filter_factory(),
    "porter_stem": porter_stem_filter,
    "stemmer": porter_stem_filter,
    "kstem": porter_stem_filter,
    "snowball": porter_stem_filter,
    "unique": unique_filter,
    "shingle": shingle_filter_factory(),
    "length": length_filter_factory(),
    "trim": trim_filter,
    "reverse": reverse_filter,
    "truncate": truncate_filter_factory(),
    "decimal_digit": decimal_digit_filter,
    "cjk_width": cjk_width_filter,
    "elision": elision_filter_factory(),
    "apostrophe": apostrophe_filter,
    "edge_ngram": edge_ngram_filter_factory(),
    "edgeNGram": edge_ngram_filter_factory(),
    "ngram": ngram_filter_factory(),
    "nGram": ngram_filter_factory(),
    "word_delimiter": word_delimiter_filter_factory({}),
}

# tokenizers defined below the static table register here
TOKENIZERS["uax_url_email"] = uax_url_email_tokenizer
TOKENIZERS["edge_ngram"] = edge_ngram_tokenizer_factory()
TOKENIZERS["path_hierarchy"] = path_hierarchy_tokenizer_factory()
TOKENIZERS["pattern"] = pattern_tokenizer_factory()

# Parameterized component factories, used for custom definitions in index
# settings (``analysis.tokenizer.<name>.type`` / ``analysis.filter.<name>.type``).
TOKENIZER_FACTORIES: dict[str, Callable[..., Tokenizer]] = {
    "ngram": lambda params: ngram_tokenizer_factory(
        int(params.get("min_gram", 1)), int(params.get("max_gram", 2))),
    "edge_ngram": lambda params: edge_ngram_tokenizer_factory(
        int(params.get("min_gram", 1)), int(params.get("max_gram", 2))),
    "pattern": lambda params: pattern_tokenizer_factory(
        str(params.get("pattern", r"\W+")), int(params.get("group", -1))),
    "path_hierarchy": lambda params: path_hierarchy_tokenizer_factory(
        str(params.get("delimiter", "/"))),
}

TOKEN_FILTER_FACTORIES: dict[str, Callable[..., TokenFilter]] = {
    "stop": lambda params: stop_filter_factory(
        frozenset(params["stopwords"]) if isinstance(params.get("stopwords"), list)
        else ENGLISH_STOPWORDS),
    "length": lambda params: length_filter_factory(
        int(params.get("min", 0)), int(params.get("max", 255))),
    "shingle": lambda params: shingle_filter_factory(
        int(params.get("min_shingle_size", 2)),
        int(params.get("max_shingle_size", 2)),
        params.get("token_separator", " ")),
    "truncate": lambda params: truncate_filter_factory(
        int(params.get("length", 10))),
    "limit": lambda params: limit_filter_factory(
        int(params.get("max_token_count", 1))),
    "elision": lambda params: elision_filter_factory(
        params.get("articles")),
    "keep": lambda params: keep_filter_factory(
        params.get("keep_words", [])),
    "edge_ngram": lambda params: edge_ngram_filter_factory(
        int(params.get("min_gram", 1)), int(params.get("max_gram", 2))),
    "ngram": lambda params: ngram_filter_factory(
        int(params.get("min_gram", 1)), int(params.get("max_gram", 2))),
    "pattern_replace": lambda params: pattern_replace_filter_factory(
        str(params.get("pattern", "")),
        str(params.get("replacement", ""))),
    "synonym": lambda params: synonym_filter_factory(
        params.get("synonyms", [])),
    "word_delimiter": word_delimiter_filter_factory,
}


# ---------------------------------------------------------------------------
# Analyzers
# ---------------------------------------------------------------------------

class Analyzer:
    def __init__(self, name: str, tokenizer: Tokenizer,
                 filters: Sequence[TokenFilter] = ()):
        self.name = name
        self.tokenizer = tokenizer
        self.filters = list(filters)

    def analyze(self, text: str) -> list[Token]:
        tokens: list[Token] = self.tokenizer(text)
        for f in self.filters:
            tokens = f(tokens)
        return tokens

    def terms(self, text: str) -> list[str]:
        return [t.term for t in self.analyze(text)]


BUILTIN_ANALYZERS: dict[str, Analyzer] = {
    # StandardAnalyzer in ES 2.x default has NO stopwords (stopwords=_none_).
    "standard": Analyzer("standard", standard_tokenizer, [lowercase_filter]),
    "simple": Analyzer("simple", letter_tokenizer, [lowercase_filter]),
    "whitespace": Analyzer("whitespace", whitespace_tokenizer),
    "keyword": Analyzer("keyword", keyword_tokenizer),
    "stop": Analyzer("stop", letter_tokenizer,
                     [lowercase_filter, stop_filter_factory()]),
    "english": Analyzer("english", standard_tokenizer,
                        [lowercase_filter, stop_filter_factory(), porter_stem_filter]),
    # SnowballAnalyzer (deprecated in Lucene 5 but still registered in ES
    # 2.x): standard tokenizer, lowercase, stop, snowball stemmer — the
    # Porter stemmer is the English snowball variant here
    "snowball": Analyzer("snowball", standard_tokenizer,
                         [lowercase_filter, stop_filter_factory(),
                          porter_stem_filter]),
}
# "default" names the index's default analyzer — standard unless the index
# overrides it (AnalysisRegistry resolves overrides; this is the fallback)
BUILTIN_ANALYZERS["default"] = BUILTIN_ANALYZERS["standard"]


class AnalysisRegistry:
    """Per-index analyzer resolution: builtins + custom chains from index
    settings (``analysis.analyzer.<name>...``), mirroring AnalysisModule."""

    def __init__(self, index_settings: Settings = Settings.EMPTY):
        self.analyzers: dict[str, Analyzer] = dict(BUILTIN_ANALYZERS)
        self.tokenizers: dict[str, Tokenizer] = dict(TOKENIZERS)
        self.tokenizers["ngram"] = ngram_tokenizer_factory()
        self.filters: dict[str, TokenFilter] = dict(TOKEN_FILTERS)
        # stored index settings carry the "index." prefix (IndexMetaData
        # normalization); analysis components must resolve either form
        index_settings = Settings(
            {(k[len("index."):] if k.startswith("index.") else k): v
             for k, v in dict(index_settings).items()})
        self._build_components(index_settings)
        self._build_custom(index_settings)

    def _component_names(self, settings: Settings, prefix: str) -> set[str]:
        return {key.split(".")[2] for key in settings if key.startswith(prefix)}

    def _build_components(self, settings: Settings) -> None:
        """Custom tokenizer/filter definitions with parameters."""
        for name in sorted(self._component_names(settings, "analysis.tokenizer.")):
            sub = settings.get_by_prefix(f"analysis.tokenizer.{name}.")
            ttype = sub.get("type")
            if ttype in TOKENIZER_FACTORIES:
                self.tokenizers[name] = TOKENIZER_FACTORIES[ttype](sub.as_dict())
            elif ttype in TOKENIZERS:
                self.tokenizers[name] = TOKENIZERS[ttype]
            else:
                raise IllegalArgumentError(f"unknown tokenizer type [{ttype}]")
        for name in sorted(self._component_names(settings, "analysis.filter.")):
            sub = settings.get_by_prefix(f"analysis.filter.{name}.")
            ftype = sub.get("type")
            if ftype in TOKEN_FILTER_FACTORIES:
                self.filters[name] = TOKEN_FILTER_FACTORIES[ftype](sub.as_dict())
            elif ftype in TOKEN_FILTERS:
                self.filters[name] = TOKEN_FILTERS[ftype]
            else:
                raise IllegalArgumentError(f"unknown filter type [{ftype}]")

    def _build_custom(self, settings: Settings) -> None:
        names = self._component_names(settings, "analysis.analyzer.")
        for name in sorted(names):
            sub = settings.get_by_prefix(f"analysis.analyzer.{name}.")
            atype = sub.get("type", "custom")
            if atype != "custom" and atype in BUILTIN_ANALYZERS:
                self.analyzers[name] = BUILTIN_ANALYZERS[atype]
                continue
            tok_name = sub.get("tokenizer", "standard")
            if tok_name not in self.tokenizers:
                raise IllegalArgumentError(f"unknown tokenizer [{tok_name}] for analyzer [{name}]")
            filters = []
            raw_filters = sub.get("filter", [])
            if isinstance(raw_filters, str):
                raw_filters = [f.strip() for f in raw_filters.split(",") if f.strip()]
            for fname in raw_filters:
                if fname not in self.filters:
                    # bare factory names act as pre-configured filters
                    # with default params (how the reference exposes
                    # plugin filters like kuromoji_baseform directly)
                    if fname in TOKEN_FILTER_FACTORIES:
                        self.filters[fname] = \
                            TOKEN_FILTER_FACTORIES[fname]({})
                    else:
                        raise IllegalArgumentError(
                            f"unknown filter [{fname}] for analyzer "
                            f"[{name}]")
                filters.append(self.filters[fname])
            self.analyzers[name] = Analyzer(name, self.tokenizers[tok_name], filters)

    def get(self, name: str) -> Analyzer:
        try:
            return self.analyzers[name]
        except KeyError:
            raise IllegalArgumentError(f"unknown analyzer [{name}]") from None
