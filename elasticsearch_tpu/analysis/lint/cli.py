"""plane-lint command line: ``estpu-lint [paths] [--json] [--rule ID]``.

Exit status 0 when every finding is suppressed (with a reason), 1 when
open findings remain, 2 on usage/parse errors — so the tier-1 gate and
any CI step can ride the exit code directly.
"""

from __future__ import annotations

import argparse
import sys

from elasticsearch_tpu.analysis.lint import (
    DEFAULT_CONFIG, RULE_FAMILIES, lint_paths)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="estpu-lint",
        description="plane-lint: AST invariant analysis for the "
                    "accelerator plane (breaker / device-seam / "
                    "recompile / lock / host-sync discipline)")
    parser.add_argument("paths", nargs="*", default=["elasticsearch_tpu"],
                        help="files or directories (default: "
                             "elasticsearch_tpu)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report with per-rule "
                             "counts")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="ID",
                        help="only report these rule ids (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print rule ids and families, then exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, family in sorted(RULE_FAMILIES.items()):
            print(f"{rid:28s} {family}")
        return 0

    result = lint_paths(args.paths, DEFAULT_CONFIG)
    if args.rule:
        unknown = set(args.rule) - set(RULE_FAMILIES)
        if unknown:
            print(f"estpu-lint: unknown rule id(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        result.findings = [f for f in result.findings
                           if f.rule in args.rule]
    print(result.to_json() if args.json else result.render())
    if result.errors:
        return 2
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
