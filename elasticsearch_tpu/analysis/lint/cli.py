"""plane-lint command line.

``estpu-lint [paths] [--json] [--rule ID] [--diff REF]
[--strict-suppressions] [--emit-lane-graph [PATH]]``

Exit status 0 when every finding is suppressed (with a reason), 1 when
open findings remain, 2 on usage/parse errors — so the tier-1 gate and
any CI step (scripts/lint_gate.sh) can ride the exit code directly.

``--diff REF`` is the incremental mode for local iteration: the
whole-program symbol table and call graph are still built over every
path (interprocedural findings need the full picture), but the REPORT
is filtered to files changed vs the git ref — so the exit code answers
"did MY change introduce a finding" without wading through the tree.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

from elasticsearch_tpu.analysis.lint import (
    DEFAULT_CONFIG, RULE_FAMILIES, lint_paths)

DEFAULT_LANE_GRAPH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "lane_graph.json")


def _changed_files(ref: str) -> "set | None":
    """Absolute paths of .py files changed vs `ref` (staged, unstaged
    and committed-after-ref), or None when git is unavailable."""
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True, text=True, check=True).stdout.strip()
        out = subprocess.run(
            ["git", "diff", "--name-only", ref, "--", "*.py"],
            capture_output=True, text=True, check=True, cwd=top).stdout
    except (OSError, subprocess.CalledProcessError) as exc:
        print(f"estpu-lint: --diff {ref} failed: {exc}", file=sys.stderr)
        return None
    return {os.path.abspath(os.path.join(top, line.strip()))
            for line in out.splitlines() if line.strip()}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="estpu-lint",
        description="plane-lint v2: whole-program invariant analysis "
                    "for the accelerator plane (breaker / device-seam / "
                    "recompile / lock / host-sync / span / trace-purity "
                    "/ counter / fallback-taxonomy / program-cost / "
                    "unbounded-wait discipline)")
    parser.add_argument("paths", nargs="*", default=["elasticsearch_tpu"],
                        help="files or directories (default: "
                             "elasticsearch_tpu)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report with per-rule "
                             "counts")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="ID",
                        help="only report these rule ids (repeatable)")
    parser.add_argument("--diff", metavar="REF", default=None,
                        help="report only findings in files changed vs "
                             "this git ref (the whole-program pass "
                             "still sees everything)")
    parser.add_argument("--strict-suppressions", action="store_true",
                        help="promote allow-stale warnings to "
                             "gate-failing findings")
    parser.add_argument("--emit-lane-graph", nargs="?", metavar="PATH",
                        const=DEFAULT_LANE_GRAPH, default=None,
                        help="write the machine-readable lane-admission "
                             "graph (default: analysis/lane_graph.json)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print rule ids and families, then exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, family in sorted(RULE_FAMILIES.items()):
            print(f"{rid:28s} {family}")
        return 0

    result = lint_paths(args.paths, DEFAULT_CONFIG,
                        strict_suppressions=args.strict_suppressions)
    if args.diff is not None:
        changed = _changed_files(args.diff)
        if changed is None:
            return 2
        result.findings = [f for f in result.findings
                           if os.path.abspath(f.path) in changed]
    if args.rule:
        unknown = set(args.rule) - set(RULE_FAMILIES)
        if unknown:
            print(f"estpu-lint: unknown rule id(s): "
                  f"{', '.join(sorted(unknown))}", file=sys.stderr)
            return 2
        result.findings = [f for f in result.findings
                           if f.rule in args.rule]
    print(result.to_json() if args.json else result.render())
    if args.emit_lane_graph is not None:
        from elasticsearch_tpu.analysis.lint.lane_graph import \
            emit_lane_graph
        graph = emit_lane_graph(result.program, args.emit_lane_graph,
                                DEFAULT_CONFIG)
        print(f"plane-lint: lane graph ({len(graph['lanes'])} lanes, "
              f"{len(graph['decline_edges'])} decline edges) → "
              f"{args.emit_lane_graph}", file=sys.stderr)
    if result.errors:
        return 2
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
