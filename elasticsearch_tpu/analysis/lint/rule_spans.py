"""span-discipline: every device seam is visible to the tracer.

``span-unscoped-site``: a ``device_fault_point(<site>)`` call must be
enclosed by — or paired with, anywhere in the same function (or an
enclosing function, mirroring the device rule's dominance walk) — a
``with device_span(<site>)`` statement naming the SAME site. Literal
sites match literal span names; inside a seam wrapper that forwards its
``site`` parameter to the fault point, the span must forward the same
parameter. An uncovered site is a device touchpoint the profile API
cannot attribute — the roofline story loses exactly the microseconds it
exists to account for.

``span-unended``: a span constructor (``device_span``) used anywhere
but as a ``with`` context expression. Spans must end on ALL exits —
success, raise, cancellation — and only the ``with`` form guarantees
it; a bare call or an assigned span leaks an open span when the region
raises. The observability package itself (where the constructors live)
is exempt.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, last_name, module_matches)


def _span_withs(cfg, fn_node) -> list:
    """(first-arg AST node) of every ``with <span_fn>(...)`` statement
    in a function body."""
    out = []
    for n in ast.walk(fn_node):
        if not isinstance(n, ast.With):
            continue
        for item in n.items:
            ce = item.context_expr
            if isinstance(ce, ast.Call) and \
                    last_name(ce.func) in cfg.span_fns and ce.args:
                out.append(ce.args[0])
    return out


def _site_covered(ctx, cfg, fn, site_arg) -> bool:
    """Is this fault point's site matched by a span with-statement in
    the enclosing function chain?"""
    if isinstance(site_arg, ast.Constant):
        def matches(arg):
            return isinstance(arg, ast.Constant) and \
                arg.value == site_arg.value
    elif isinstance(site_arg, ast.Name):
        def matches(arg):
            return isinstance(arg, ast.Name) and arg.id == site_arg.id
    else:
        return True                     # device-unknown-site's problem
    info = fn
    while info is not None:
        if any(matches(arg) for arg in _span_withs(cfg, info.node)):
            return True
        info = info.parent
    return False


def check(ctx, cfg, program=None) -> list:
    exempt = module_matches(ctx.relpath, cfg.span_exempt_modules)
    findings, nodes = [], []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = last_name(node.func)
        if name in cfg.span_fns and not exempt:
            parent = ctx.parent(node)
            if not isinstance(parent, ast.withitem):
                findings.append(Finding(
                    "span-unended", ctx.relpath, node.lineno,
                    f"{name}(...) used outside a `with` statement — a "
                    f"span must end on all exits (return, raise, "
                    f"cancellation); only the `with` form guarantees "
                    f"closure"))
                nodes.append(node)
            continue
        if name in cfg.fault_point_names and node.args:
            fn = ctx.enclosing_function(node)
            if fn is None:
                continue                # module scope: test scaffolding
            if _site_covered(ctx, cfg, fn, node.args[0]):
                continue
            site = node.args[0].value \
                if isinstance(node.args[0], ast.Constant) \
                else getattr(node.args[0], "id", "?")
            findings.append(Finding(
                "span-unscoped-site", ctx.relpath, node.lineno,
                f"device_fault_point({site!r}) in {fn.qualname}() has "
                f"no matching `with device_span({site!r})` in scope — "
                f"this device seam is invisible to the span tracer and "
                f"the profile API cannot attribute its time"))
            nodes.append(node)
    return apply_suppressions(ctx, findings, nodes)
