"""host-sync hazard: no device→host sync inside a dispatch loop.

Scope: functions in the hot-path modules (jit_exec / mesh_engine /
percolator / ops.percolate) whose body dispatches compiled programs —
marked by a ``device_fault_point`` call with a dispatch-class site
(``dispatch`` / ``plane-dispatch`` / ``percolate``) or a
``_get_compiled`` call.

Inside such a function, a ``for``/``while`` loop that CONTAINS a
dispatch marker must not also host-sync per iteration: the async
dispatch pipeline (groups/segments overlapping on device) serializes
the moment the loop body forces a transfer. Flagged syncs:

* ``np.asarray(...)`` / ``.item()`` on anything;
* ``jax.block_until_ready`` / ``.block_until_ready()``;
* ``float()`` / ``int()`` / ``bool()`` applied to a dispatch RESULT —
  a name bound from calling a ``_get_compiled``-produced program;
* (v2, interprocedural) a call to a function that TRANSITIVELY
  host-syncs — resolved through the whole-program call graph, so
  hoisting the ``np.asarray`` into a helper no longer hides it from
  the rule. Seam wrappers (``seam_device_put``) are exempt: their
  transfer is host→device staging, not a pipeline stall.

Syncs after the loop (drain-at-the-end) are the intended shape and pass.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, dotted, last_name, module_matches)


def _dispatch_markers(fn_node, cfg) -> list:
    out = []
    for n in ast.walk(fn_node):
        if not isinstance(n, ast.Call):
            continue
        name = last_name(n.func)
        if name in cfg.trampolines:
            out.append(n)
        elif name in cfg.fault_point_names and n.args and \
                isinstance(n.args[0], ast.Constant) and \
                n.args[0].value in cfg.dispatch_sites:
            out.append(n)
    return out


def _dispatch_result_names(fn_node, cfg) -> set:
    """Names bound from invoking a compiled program: `fn =
    _get_compiled(...)` (or self._program(...)) then `out = fn(...)`."""
    program_names: set = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            callee = last_name(n.value.func)
            if callee in cfg.trampolines or callee == "_program":
                program_names.update(
                    t.id for t in n.targets if isinstance(t, ast.Name))
    results: set = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and isinstance(n.value.func, ast.Name) and \
                n.value.func.id in program_names:
            results.update(
                t.id for t in n.targets if isinstance(t, ast.Name))
    return results


def _base_name(expr) -> str:
    while isinstance(expr, (ast.Subscript, ast.Attribute)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else ""


def _sync_calls(loop, results: set):
    for n in ast.walk(loop):
        if not isinstance(n, ast.Call):
            continue
        d = dotted(n.func)
        name = last_name(n.func)
        # dotted() gives '' when the receiver is a call/subscript chain
        # (`program(h).item()`), so method matches use the raw attr
        attr = n.func.attr if isinstance(n.func, ast.Attribute) else ""
        if d == "np.asarray" or d == "numpy.asarray":
            yield n, "np.asarray forces a device→host transfer"
        elif attr == "item" and not n.args:
            yield n, ".item() forces a device→host transfer"
        elif name == "block_until_ready" or attr == "block_until_ready":
            yield n, "block_until_ready stalls the dispatch pipeline"
        elif name in ("float", "int", "bool") and n.args and \
                _base_name(n.args[0]) in results:
            yield (n, f"{name}() on a dispatch result synchronizes "
                      f"the device")


def _syncing_fqns(program):
    """fqn → first direct sync line for functions whose body host-syncs,
    plus the transitive closure of their callers' view: everything that
    REACHES a sync. Cached on the program."""
    cached = getattr(program, "_hostsync_syncing", None)
    if cached is not None:
        return cached
    direct: dict = {}
    for fqn, (fctx, info) in program.functions.items():
        for call, why in _sync_calls(info.node, set()):
            direct[fqn] = (fctx.relpath, call.lineno, why)
            break
    marked = program.transitive_marked(set(direct))
    cached = (direct, marked)
    program._hostsync_syncing = cached
    return cached


def _callee_syncs(ctx, cfg, program, fn, loop):
    """(call node, message) for loop-body calls that resolve to a
    function which (transitively) host-syncs."""
    if program is None:
        return
    direct, marked = _syncing_fqns(program)
    for n in ast.walk(loop):
        if not isinstance(n, ast.Call):
            continue
        name = last_name(n.func)
        if name in cfg.seam_wrappers or name in cfg.fault_point_names \
                or name in cfg.span_fns or name in cfg.trampolines:
            continue                    # staging/guard seams, not syncs
        targets = program.resolve_callable(ctx, n.func, fn)
        hit = sorted(t for t in targets if t in marked)
        if not hit:
            continue
        site = direct.get(hit[0])
        where = f" (sync at {site[0]}:{site[1]})" if site else ""
        yield n, (f"call to {hit[0].rsplit('.', 1)[-1]}() which "
                  f"transitively forces a device→host sync{where}")


def check(ctx, cfg, program=None) -> list:
    if not module_matches(ctx.relpath, cfg.hot_modules):
        return []
    findings, nodes = [], []
    for fn in ctx.functions:
        markers = _dispatch_markers(fn.node, cfg)
        if not markers:
            continue
        results = _dispatch_result_names(fn.node, cfg)
        seen: set = set()
        for loop in ast.walk(fn.node):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            marker_lines = {m.lineno for m in markers
                            if _contains(loop, m)}
            if not marker_lines:
                continue
            direct_syncs = list(_sync_calls(loop, results))
            direct_ids = {id(c) for c, _ in direct_syncs}
            indirect = [(c, w) for c, w in
                        _callee_syncs(ctx, cfg, program, fn, loop)
                        if id(c) not in direct_ids]
            for call, why in direct_syncs + indirect:
                if id(call) in seen:
                    continue
                seen.add(id(call))
                findings.append(Finding(
                    "host-sync-hot-loop", ctx.relpath, call.lineno,
                    f"{why} inside the dispatch loop of "
                    f"{fn.qualname}() (dispatch at line "
                    f"{min(marker_lines)}) — sync after the loop so "
                    f"dispatches pipeline"))
                nodes.append(call)
    return apply_suppressions(ctx, findings, nodes)


def _contains(outer, inner) -> bool:
    return any(n is inner for n in ast.walk(outer))
