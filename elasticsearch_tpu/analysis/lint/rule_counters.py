"""counter-discipline: no silent counters, no dead keys.

Every stats counter bumped in the counter modules (jit_exec /
mesh_engine / percolator) must be declared in the central lane registry
(``elasticsearch_tpu.search.lanes``), and every registered key must be
bumped somewhere — the two orphan directions:

* ``counter-unregistered`` — a bump (``_bump("key")``,
  ``_stats["key"] += n``, ``self.stats["key"] += n``) whose key is not
  in any registry dict, or whose key cannot be statically resolved: a
  counter nobody can find in ``_nodes/stats`` documentation, or a typo
  that silently splits a metric;
* ``counter-unbumped`` — a registered key with zero bump sites across
  the whole program: it surfaces as an eternally-zero stat that reads
  like a healthy system;
* ``counter-unsurfaced`` — a counter STORE in a counter module
  initialized from a hand-written dict literal instead of the registry
  (``{k: 0 for k in lanes.X}``): the store's keys and the registry
  drift apart invisibly;
* ``counter-unexported`` — a registry dict the OpenMetrics exporter
  module never references: the exposition is registry-DRIVEN (it
  iterates each registry, so a referenced registry exports every key
  by construction), which makes an unreferenced registry a whole
  counter family invisible to ``/_prometheus/metrics``. Skipped when
  no exporter module is in the linted set (fixture runs).

Bump recognition: AugAssign on a store subscript, a positive-constant
Assign (``stats["builds"] = 1`` — counted at construction), and
``_bump(key)`` calls; keys resolve through string constants, either
branch of a conditional expression, and one level of
``key = {...}[kind]`` dict-literal indirection. Inside a bump helper
itself (``_bump``), the forwarded parameter is exempt — its literals
are checked at every call site instead.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, last_name, module_matches)
from elasticsearch_tpu.analysis.lint.program import (
    literal_assignment, modkey_for)


def _registry(program, cfg) -> "dict | None":
    """key → (registry name, relpath, line) over every registry dict, or
    None when no registry module is in the linted set (single-file runs
    skip the rule rather than flagging everything)."""
    out: dict = {}
    found = False
    for ctx in program.registry_contexts(cfg.counter_registry_modules):
        for name in cfg.counter_registry_names:
            value = literal_assignment(ctx.tree, name)
            if not isinstance(value, ast.Dict):
                continue
            found = True
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out[k.value] = (name, ctx.relpath, k.lineno)
    return out if found else None


def _key_literals(ctx, fn_node, expr) -> "list | None":
    """String keys an index/argument expression can take: constants,
    conditional-expression branches, and a Name bound (once, in this
    function) to a dict-literal subscript — ``key = {...}[kind]`` takes
    every dict VALUE. None when not statically resolvable."""
    if isinstance(expr, ast.Constant):
        return [expr.value] if isinstance(expr.value, str) else None
    if isinstance(expr, ast.IfExp):
        a = _key_literals(ctx, fn_node, expr.body)
        b = _key_literals(ctx, fn_node, expr.orelse)
        if a is not None and b is not None:
            return a + b
        return None
    if isinstance(expr, ast.Name):
        bound = None
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in n.targets):
                bound = n.value
        if isinstance(bound, ast.Subscript) and \
                isinstance(bound.value, ast.Dict):
            vals = [v.value for v in bound.value.values
                    if isinstance(v, ast.Constant)]
            return vals if len(vals) == len(bound.value.values) else None
        if bound is not None:
            return _key_literals(ctx, fn_node, bound)
    return None


def _store_match(ctx, mod_names: set, target, cfg) -> str | None:
    """Is `target` (the subscripted value) a counter store? Bare names
    must be module-level (a function-local ``stats = {...}`` scratch
    dict is not a store); ``self.<store>`` attributes always match."""
    if isinstance(target, ast.Name):
        if target.id in cfg.counter_stores and target.id in mod_names:
            return target.id
    elif isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name) and \
            target.value.id == "self" and target.attr in cfg.counter_stores:
        return target.attr
    return None


def check_program(program, cfg) -> list:
    registry = _registry(program, cfg)
    if registry is None:
        return []
    counter_ctxs = [ctx for ctx in program.contexts
                    if module_matches(ctx.relpath, cfg.counter_modules)]
    if not counter_ctxs:
        return []

    bumped: set = set()
    by_ctx: dict = {}

    def report(ctx, rule, node, message):
        _, findings, nodes = by_ctx.setdefault(ctx.relpath, (ctx, [], []))
        findings.append(Finding(rule, ctx.relpath, node.lineno, message))
        nodes.append(node)

    for ctx in counter_ctxs:
        mod = program.modules.get(modkey_for(ctx.relpath))
        mod_names = mod.module_names if mod is not None else set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.stmt, ast.expr)):
                continue                  # ctx/operator singletons share
                                          # parent links across trees
            fn = ctx.enclosing_function(node)
            fn_node = fn.node if fn is not None else ctx.tree
            # ---- store subscript writes ------------------------------
            target = slice_expr = None
            counted = True
            if isinstance(node, ast.AugAssign) and \
                    isinstance(node.target, ast.Subscript):
                target, slice_expr = node.target.value, node.target.slice
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        target, slice_expr = t.value, t.slice
                # plain assignment only counts as a bump for a positive
                # constant (counted-at-construction); zero re-inits are
                # declarations
                counted = isinstance(node.value, ast.Constant) and \
                    isinstance(node.value.value, (int, float)) and \
                    node.value.value > 0
                # store initialized from a literal dict: keys must come
                # from the registry comprehension, not hand-written
                if target is None and isinstance(
                        node.value, (ast.Dict, ast.DictComp)):
                    store = None
                    for t in node.targets:
                        store = store or _store_match(
                            ctx, mod_names, t, cfg)
                    if store is not None:
                        if isinstance(node.value, ast.DictComp):
                            it = node.value.generators[0].iter
                            if last_name(it) not in \
                                    cfg.counter_registry_names:
                                report(ctx, "counter-unsurfaced", node,
                                       f"counter store [{store}] is "
                                       f"built from "
                                       f"[{last_name(it) or '?'}], not "
                                       f"a registry dict — registry "
                                       f"and surface drift apart")
                        else:
                            report(ctx, "counter-unsurfaced", node,
                                   f"counter store [{store}] is "
                                   f"initialized from a hand-written "
                                   f"literal — build it from the "
                                   f"registry ({{k: 0 for k in "
                                   f"lanes.<REGISTRY>}}) so every "
                                   f"registered key is surfaced by "
                                   f"construction")
                    continue
            if target is not None:
                store = _store_match(ctx, mod_names, target, cfg)
                if store is None or not counted:
                    continue
                # a bump-helper's own forwarded parameter: literals are
                # checked at its call sites
                if isinstance(slice_expr, ast.Name) and fn is not None \
                        and fn.name in cfg.counter_bump_fns and \
                        slice_expr.id in {
                            a.arg for a in fn.node.args.args +
                            fn.node.args.kwonlyargs}:
                    continue
                keys = _key_literals(ctx, fn_node, slice_expr)
                if keys is None:
                    report(ctx, "counter-unregistered", node,
                           f"counter key into [{store}] is not "
                           f"statically resolvable — use a string "
                           f"literal (or a dict-literal lookup) so the "
                           f"registry check can see it")
                    continue
                for key in keys:
                    bumped.add(key)
                    if key not in registry:
                        report(ctx, "counter-unregistered", node,
                               f"counter [{key}] bumped into [{store}] "
                               f"is not declared in the lane registry "
                               f"— a silent counter (or a typo "
                               f"splitting a metric)")
                continue
            # ---- bump-helper calls -----------------------------------
            if isinstance(node, ast.Call) and \
                    last_name(node.func) in cfg.counter_bump_fns and \
                    node.args:
                keys = _key_literals(ctx, fn_node, node.args[0])
                if keys is None:
                    report(ctx, "counter-unregistered", node,
                           f"{last_name(node.func)}() key is not "
                           f"statically resolvable — use a string "
                           f"literal so the registry check can see it")
                    continue
                for key in keys:
                    bumped.add(key)
                    if key not in registry:
                        report(ctx, "counter-unregistered", node,
                               f"counter [{key}] bumped via "
                               f"{last_name(node.func)}() is not "
                               f"declared in the lane registry")

    out = []
    for ctx, findings, nodes in by_ctx.values():
        out.extend(apply_suppressions(ctx, findings, nodes))

    # ---- the exporter orphan: registered but never exported --------------
    reg_by_path = {ctx.relpath: ctx for ctx in
                   program.registry_contexts(cfg.counter_registry_modules)}
    exporter_ctxs = [ctx for ctx in program.contexts
                     if module_matches(ctx.relpath, cfg.exporter_modules)]
    if exporter_ctxs:
        referenced: set = set()
        for ctx in exporter_ctxs:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Attribute):
                    referenced.add(node.attr)
                elif isinstance(node, ast.Name):
                    referenced.add(node.id)
        reg_lines: dict = {}
        for key, (name, relpath, line) in registry.items():
            cur = reg_lines.get(name)
            if cur is None or line < cur[1]:
                reg_lines[name] = (relpath, line)
        for name in sorted(reg_lines):
            if name in referenced:
                continue
            relpath, line = reg_lines[name]
            f = Finding(
                "counter-unexported", relpath, line,
                f"registry [{name}] is never referenced by the "
                f"OpenMetrics exporter "
                f"({', '.join(c.relpath for c in exporter_ctxs)}) — "
                f"its whole counter family is invisible to "
                f"/_prometheus/metrics; iterate it in the exposition "
                f"so every key exports by construction")
            ctx = reg_by_path.get(relpath)
            if ctx is not None:
                for ln in (line - 1, line):
                    for rid, reason in ctx.suppressions.get(ln, ()):
                        if rid == f.rule and reason:
                            ctx.used_suppressions.add((ln, rid))
                            f.suppressed, f.suppress_reason = True, reason
            out.append(f)

    # ---- the reverse orphan: registered but never bumped -----------------
    for key, (name, relpath, line) in sorted(registry.items()):
        if key in bumped:
            continue
        f = Finding("counter-unbumped", relpath, line,
                    f"registered counter [{key}] ({name}) has no bump "
                    f"site anywhere in the program — it surfaces as an "
                    f"eternally-zero stat that reads like a healthy "
                    f"system")
        ctx = reg_by_path.get(relpath)
        if ctx is not None:
            hit = None
            for ln in (line - 1, line):
                for rid, reason in ctx.suppressions.get(ln, ()):
                    if rid == f.rule and reason:
                        hit = (ln, reason)
            if hit is not None:
                ctx.used_suppressions.add((hit[0], f.rule))
                f.suppressed, f.suppress_reason = True, hit[1]
        out.append(f)
    return out
