"""Machine-readable lane-admission graph (``estpu-lint --emit-lane-graph``).

The fallback-taxonomy pass doubles as an extractor: this module renders
the lane registry (``elasticsearch_tpu.search.lanes``) TOGETHER with
what the whole-program analysis actually found on the tree —

* per lane: the admission predicate's resolved source location and the
  reason vocabulary with the file:line of every reason-labeled decline
  site;
* the pairwise decline edges (``plane`` cedes to ``impact`` under
  ``impact-preferred``, …) with their sites;
* the counter registries (so the planner sees the lanes' observable
  surface too).

The emitted ``analysis/lane_graph.json`` is the lane model ROADMAP
item 3's unified planner consumes; tests/test_lane_graph.py round-trips
it against the live runtime registries every tier-1 run, so the
artifact can never drift from the code. Paths are normalized to be
package-relative and the JSON is key-sorted — the file is byte-stable
across working directories.
"""

from __future__ import annotations

import ast
import json

from elasticsearch_tpu.analysis.lint.context import DEFAULT_CONFIG
from elasticsearch_tpu.analysis.lint.program import (
    const_of, literal_assignment)
from elasticsearch_tpu.analysis.lint import rule_fallback


def _norm(relpath: str) -> str:
    """Package-relative path: byte-identical no matter where the lint
    ran from."""
    rel = relpath.replace("\\", "/")
    marker = "elasticsearch_tpu/"
    idx = rel.rfind(marker)
    return rel[idx:] if idx >= 0 else rel


def _registry_value(program, cfg, name):
    for ctx in program.registry_contexts(cfg.lane_registry_modules):
        value = literal_assignment(ctx.tree, name)
        if value is not None:
            try:
                return const_of(value)
            except ValueError:
                return None
    return None


def _admission_location(program, spec: str) -> "dict | None":
    """Resolve "pkg-relative-path::Qualname" against the program's
    function table → {"function", "path", "line"}, or None when the
    spec no longer matches (the round-trip test fails loudly on that)."""
    path, _, qual = spec.partition("::")
    for fqn, (ctx, info) in program.functions.items():
        if info.qualname == qual and \
                _norm(ctx.relpath) == _norm(path):
            return {"function": qual, "path": _norm(ctx.relpath),
                    "line": info.node.lineno}
    return None


def build_lane_graph(program, cfg=DEFAULT_CONFIG) -> dict:
    reasons_reg = _registry_value(program, cfg, cfg.lane_reasons_name) \
        or {}
    edges_reg = _registry_value(program, cfg, cfg.lane_edges_name) or ()
    admissions_reg = _registry_value(program, cfg,
                                     cfg.lane_admissions_name) or {}

    sites: dict = {}                      # (lane, reason) → [{path, line}]
    for lane, reasons, ctx, node in rule_fallback.iter_reason_sites(
            program, cfg):
        for r in reasons or ():
            sites.setdefault((lane, r), []).append(
                {"path": _norm(ctx.relpath), "line": node.lineno})
    for key in sites:
        sites[key].sort(key=lambda s: (s["path"], s["line"]))

    lanes_out: dict = {}
    for lane in sorted(reasons_reg):
        spec = admissions_reg.get(lane)
        lanes_out[lane] = {
            "admission": (_admission_location(program, spec)
                          if spec else None),
            "reasons": {r: sites.get((lane, r), [])
                        for r in reasons_reg[lane]},
        }

    edges_out = [{"from": a, "to": b, "reason": r,
                  "sites": sites.get((a, r), [])}
                 for a, b, r in edges_reg]

    counters_out = {}
    # gauge registries (PROGRAM_COST — the cost observatory's exported
    # surface) ride next to the counter registries: the planner reads
    # the lanes' observable cost fields from the same artifact as their
    # admission model
    for name in (tuple(cfg.counter_registry_names) +
                 tuple(getattr(cfg, "gauge_registry_names", ()))):
        for ctx in program.registry_contexts(cfg.counter_registry_modules):
            value = literal_assignment(ctx.tree, name)
            if isinstance(value, ast.Dict):
                counters_out[name] = sorted(
                    k.value for k in value.keys
                    if isinstance(k, ast.Constant))

    # the program-lane vocabulary (lanes.PROGRAM_LANES) — the cost
    # observatory's lane axis, alongside the serving-lane reasons
    program_lanes = None
    for ctx in program.registry_contexts(cfg.lane_registry_modules):
        value = literal_assignment(ctx.tree, "PROGRAM_LANES")
        if value is not None:
            try:
                program_lanes = sorted(const_of(value))
            except ValueError:
                program_lanes = None

    return {
        "version": 1,
        "tool": "plane-lint",
        "lanes": lanes_out,
        "decline_edges": edges_out,
        "counters": counters_out,
        "program_lanes": program_lanes or [],
    }


def render_lane_graph(graph: dict) -> str:
    return json.dumps(graph, indent=2, sort_keys=True) + "\n"


def emit_lane_graph(program, out_path: str, cfg=DEFAULT_CONFIG) -> dict:
    graph = build_lane_graph(program, cfg)
    with open(out_path, "w", encoding="utf-8") as fh:
        fh.write(render_lane_graph(graph))
    return graph
