"""program-cost-discipline: every program compile is observed.

The cost observatory (observability/costs.py) can only model what it
sees: a ``.lower(...).compile(...)`` that bypasses the
``jit_exec.observed_compile`` seam produces a compiled program with no
cost-table row — invisible to ``/_cat/programs``, unpriceable by the
planner's ``estimate()``, and missing from the predicted-vs-measured
accounting. Config-driven like the device-seam upload_sites family:

* ``program-cost-unobserved`` — inside the cost seam modules
  (``cfg.cost_seam_modules``: jit_exec / mesh_engine), a ``.compile()``
  call on a lowered program — the direct
  ``jax.jit(f).lower(...).compile()`` chain, or a ``.compile()`` on a
  local previously bound to a ``.lower(...)`` result — anywhere except
  inside a registered seam function (``cfg.cost_seam_fns``:
  ``observed_compile``) is an error: route the LOWERED program through
  the seam and let it own the ``.compile()``.

* ``program-cost-unknown-lane`` — a call to a lane-taking entry point
  (``cfg.cost_lane_callers``: ``observed_compile`` / ``_get_compiled``)
  whose ``lane`` argument is not a string literal from
  ``cfg.program_lanes`` (mirroring ``lanes.PROGRAM_LANES``) — or is
  missing entirely. The closed-vocabulary discipline of
  ``device-unknown-site``: a misspelled lane silently splits a
  program's books. Inside a lane caller itself a forwarded ``lane``
  parameter is exempt (its literals are checked at every call site —
  the seam-wrapper idiom).
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, last_name, module_matches)


def _is_lower_call(node) -> bool:
    return isinstance(node, ast.Call) and \
        isinstance(node.func, ast.Attribute) and \
        node.func.attr == "lower"


def _lower_bound_names(fn_node) -> set:
    """Names bound (anywhere in `fn_node`) to a ``.lower(...)`` call
    result — ``lowered = jax.jit(f).lower(*shapes)``."""
    out = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Assign) and _is_lower_call(n.value):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


def _in_seam_fn(ctx, node, cfg) -> bool:
    for info in ctx.enclosing_chain(node):
        if info.name in cfg.cost_seam_fns:
            return True
    return False


def _lane_arg(call: ast.Call, fn_name: str):
    """The ``lane`` argument expression of a lane-caller call, or None
    when absent. observed_compile takes lane positionally first;
    _get_compiled takes it as the third positional or ``lane=``."""
    for kw in call.keywords:
        if kw.arg == "lane":
            return kw.value
    if fn_name == "observed_compile" and call.args:
        return call.args[0]
    if fn_name == "_get_compiled" and len(call.args) >= 3:
        return call.args[2]
    return None


def check(ctx, cfg, program=None) -> list:
    findings, nodes = [], []
    in_cost_seam = module_matches(ctx.relpath, cfg.cost_seam_modules)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue

        # ---- lane literals at the seam entry points ----------------------
        fn_name = last_name(node.func)
        if fn_name in cfg.cost_lane_callers:
            lane = _lane_arg(node, fn_name)
            ok = isinstance(lane, ast.Constant) and \
                lane.value in cfg.program_lanes
            if not ok and isinstance(lane, ast.Name):
                # forwarded parameter inside a lane caller itself:
                # checked at that caller's call sites instead
                enc = ctx.enclosing_function(node)
                if enc is not None and enc.name in cfg.cost_lane_callers:
                    params = {a.arg for a in enc.node.args.args +
                              enc.node.args.kwonlyargs}
                    ok = lane.id in params
            if not ok:
                findings.append(Finding(
                    "program-cost-unknown-lane", ctx.relpath,
                    node.lineno,
                    f"{fn_name}() lane must be a string literal from "
                    f"{sorted(cfg.program_lanes)} "
                    f"(lanes.PROGRAM_LANES) — an unregistered lane "
                    f"splits the program's cost books"))
                nodes.append(node)
            continue

        # ---- unobserved compiles inside the seam modules -----------------
        if not in_cost_seam:
            continue
        if not (isinstance(node.func, ast.Attribute) and
                node.func.attr == "compile"):
            continue
        recv = node.func.value
        direct = _is_lower_call(recv)
        via_name = False
        if isinstance(recv, ast.Name):
            fn = ctx.enclosing_function(node)
            scope = fn.node if fn is not None else ctx.tree
            via_name = recv.id in _lower_bound_names(scope)
        if not (direct or via_name):
            continue
        if _in_seam_fn(ctx, node, cfg):
            continue
        findings.append(Finding(
            "program-cost-unobserved", ctx.relpath, node.lineno,
            f".lower(...).compile(...) outside "
            f"{'/'.join(cfg.cost_seam_fns)} — this program never "
            f"reaches the cost observatory (no /_cat/programs row, no "
            f"estimate()); return the LOWERED program and route it "
            f"through jit_exec.observed_compile"))
        nodes.append(node)

    return apply_suppressions(ctx, findings, nodes)
