"""plane-lint v2: whole-program invariant analysis for the accelerator
plane.

Twelve rule families over the ``elasticsearch_tpu`` tree — breaker
discipline, device-seam coverage, recompile hazards, lock discipline,
host-sync hazards, span discipline, trace purity, counter discipline,
fallback taxonomy, program-cost discipline, unbounded-wait,
plan-node-spans — each with
inline suppressions
(``# estpu: allow[rule-id] <reason>``), machine-readable output, and a
tier-1 tree-is-clean gate (tests/test_static_analysis.py).

v2 upgraded the analyzer from per-file AST matching to a whole-program
pass: every run builds a project-wide symbol table and call graph
(:class:`~elasticsearch_tpu.analysis.lint.program.ProgramIndex`), so
breaker release-reachability, lock-order edges and host-sync detection
follow calls across module boundaries, and three interprocedural
families ride the same index — trace-purity (nothing reachable from a
``seam_jit``/``jax.jit``/``vmap``/``lax.scan`` region may import,
write module state, or side-effect), counter-discipline (every bump
registered in ``search/lanes.py``, every registered key bumped), and
fallback-taxonomy (one closed decline-reason vocabulary per lane).
The taxonomy pass doubles as an extractor: ``estpu-lint
--emit-lane-graph`` writes ``analysis/lane_graph.json``
(:mod:`~elasticsearch_tpu.analysis.lint.lane_graph`).

Run it::

    python -m elasticsearch_tpu.analysis [paths] [--json]
    estpu-lint elasticsearch_tpu/
    estpu-lint --diff origin/main          # findings in changed files only
    estpu-lint --emit-lane-graph           # + write the lane model

API::

    result = lint_paths(["elasticsearch_tpu"])
    result.unsuppressed        # findings the gate fails on
    result.warnings            # stale-suppression audit (warning tier)
    result.to_json()           # stamped with per-family rule counts
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from elasticsearch_tpu.analysis.lint.context import (
    DEFAULT_CONFIG, Finding, LintConfig, ModuleContext, RULE_FAMILIES)
from elasticsearch_tpu.analysis.lint import (
    rule_breaker, rule_costs, rule_counters, rule_device, rule_fallback,
    rule_hostsync, rule_locks, rule_planspans, rule_recompile,
    rule_spans, rule_trace, rule_waits)
from elasticsearch_tpu.analysis.lint.program import ProgramIndex

__all__ = ["Finding", "LintConfig", "LintResult", "DEFAULT_CONFIG",
           "RULE_FAMILIES", "lint_paths", "iter_py_files"]

_PER_MODULE_RULES = (rule_breaker.check, rule_costs.check,
                     rule_device.check,
                     rule_recompile.check, rule_hostsync.check,
                     rule_locks.check_state, rule_spans.check,
                     rule_waits.check)
_PROGRAM_RULES = (rule_trace.check_program, rule_counters.check_program,
                  rule_fallback.check_program,
                  rule_planspans.check_program)


@dataclass
class LintResult:
    findings: list = field(default_factory=list)
    files: int = 0
    errors: list = field(default_factory=list)   # unparseable files
    #: the whole-program index the rules ran over (lane-graph emission
    #: and the test suite reuse it)
    program: "ProgramIndex | None" = None

    @property
    def unsuppressed(self) -> list:
        return [f for f in self.findings
                if not f.suppressed and not f.warning]

    @property
    def suppressed(self) -> list:
        return [f for f in self.findings if f.suppressed]

    @property
    def warnings(self) -> list:
        return [f for f in self.findings
                if f.warning and not f.suppressed]

    def counts(self) -> dict:
        by_rule: dict = {}
        by_family: dict = {}
        for f in self.findings:
            key = "suppressed" if f.suppressed else \
                ("warning" if f.warning else "open")
            by_rule.setdefault(f.rule, {"open": 0, "suppressed": 0,
                                        "warning": 0})
            by_rule[f.rule][key] += 1
            by_family.setdefault(f.family, {"open": 0, "suppressed": 0,
                                            "warning": 0})
            by_family[f.family][key] += 1
        return {"rules": by_rule, "families": by_family}

    def to_json(self) -> str:
        return json.dumps({
            "tool": "plane-lint",
            "version": 2,
            "files": self.files,
            "findings": [f.to_dict() for f in self.findings],
            "counts": self.counts(),
            "open": len(self.unsuppressed),
            "suppressed": len(self.suppressed),
            "warnings": len(self.warnings),
            "parse_errors": self.errors,
        }, indent=2, sort_keys=True)

    def render(self) -> str:
        lines = [f.render() for f in sorted(
            self.findings, key=lambda f: (f.path, f.line, f.rule))]
        counts = self.counts()["families"]
        fam = ", ".join(f"{name}: {c['open']}+{c['suppressed']}a"
                        for name, c in sorted(counts.items()))
        lines.append(
            f"plane-lint: {len(self.unsuppressed)} finding(s), "
            f"{len(self.warnings)} warning(s), "
            f"{len(self.suppressed)} allowed, {self.files} file(s)"
            + (f" [{fam}]" if fam else ""))
        for path, err in self.errors:
            lines.append(f"plane-lint: parse error in {path}: {err}")
        return "\n".join(lines)


def iter_py_files(paths) -> list:
    out = []
    for path in paths:
        if os.path.isfile(path) and path.endswith(".py"):
            out.append(path)
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__",))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    return out


def _relpath(path: str) -> str:
    rel = os.path.relpath(path)
    return rel.replace(os.sep, "/")


def parse_contexts(paths) -> "tuple[list, list]":
    """([ModuleContext], [(relpath, error)]) over every .py under
    `paths` — the parse front half of lint_paths, reusable by the
    lane-graph emitter."""
    contexts, errors = [], []
    for path in iter_py_files(paths):
        rel = _relpath(path)
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            contexts.append(ModuleContext(rel, src))
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            errors.append((rel, str(exc)))
    return contexts, errors


def lint_paths(paths, config: LintConfig = DEFAULT_CONFIG, *,
               strict_suppressions: bool = False) -> LintResult:
    result = LintResult()
    contexts, result.errors = parse_contexts(paths)
    result.files = len(contexts)
    program = ProgramIndex(contexts, config)
    result.program = program

    lock_infos = []
    by_rel = {}
    for ctx in contexts:
        by_rel[ctx.relpath] = ctx
        for rule in _PER_MODULE_RULES:
            result.findings.extend(rule(ctx, config, program))
        result.findings.extend(ctx.meta_findings())
        lock_infos.append(rule_locks.collect(ctx, config))

    # whole-program rule families (trace purity / counters / taxonomy)
    for rule in _PROGRAM_RULES:
        result.findings.extend(rule(program, config))

    # cross-module lock-order pass (suppressible at the acquisition line)
    for f in rule_locks.finalize(lock_infos, config, program):
        ctx = by_rel.get(f.path)
        if ctx is not None:
            for line in (f.line - 1, f.line):
                for rid, reason in ctx.suppressions.get(line, ()):
                    if rid == f.rule and reason:
                        ctx.used_suppressions.add((line, rid))
                        f.suppressed = True
                        f.suppress_reason = reason
        result.findings.append(f)

    # stale-suppression audit: runs LAST, after every rule consumed its
    # allows — a reasoned allow nothing matched is dead weight
    for ctx in contexts:
        result.findings.extend(ctx.stale_findings(strict_suppressions))
    return result


def lock_graph_for(paths, config: LintConfig = DEFAULT_CONFIG):
    """(edges, ranks) of the static lock-acquisition graph — the runtime
    watchdog (elasticsearch_tpu.analysis.watchdog) consumes this. Rides
    the same whole-program index as the lint rules, so the watchdog
    asserts exactly the graph the static rule reports on."""
    contexts, _ = parse_contexts(paths)
    program = ProgramIndex(contexts, config)
    infos = [rule_locks.collect(ctx, config) for ctx in contexts]
    edges = rule_locks.lock_graph(infos, config, program)
    return edges, rule_locks.lock_ranks(edges)
