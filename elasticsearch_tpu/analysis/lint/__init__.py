"""plane-lint: AST-level invariant analysis for the accelerator plane.

Six rule families over the ``elasticsearch_tpu`` tree — breaker
discipline, device-seam coverage, recompile hazards, lock discipline,
host-sync hazards, span discipline — each with inline suppressions
(``# estpu: allow[rule-id] <reason>``), machine-readable output, and a
tier-1 tree-is-clean gate (tests/test_static_analysis.py).

Run it::

    python -m elasticsearch_tpu.analysis [paths] [--json]
    estpu-lint elasticsearch_tpu/

API::

    result = lint_paths(["elasticsearch_tpu"])
    result.unsuppressed        # findings the gate fails on
    result.to_json()           # stamped with per-family rule counts
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field

from elasticsearch_tpu.analysis.lint.context import (
    DEFAULT_CONFIG, Finding, LintConfig, ModuleContext, RULE_FAMILIES)
from elasticsearch_tpu.analysis.lint import (
    rule_breaker, rule_device, rule_hostsync, rule_locks, rule_recompile,
    rule_spans)

__all__ = ["Finding", "LintConfig", "LintResult", "DEFAULT_CONFIG",
           "RULE_FAMILIES", "lint_paths", "iter_py_files"]

_PER_MODULE_RULES = (rule_breaker.check, rule_device.check,
                     rule_recompile.check, rule_hostsync.check,
                     rule_locks.check_state, rule_spans.check)


@dataclass
class LintResult:
    findings: list = field(default_factory=list)
    files: int = 0
    errors: list = field(default_factory=list)   # unparseable files

    @property
    def unsuppressed(self) -> list:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list:
        return [f for f in self.findings if f.suppressed]

    def counts(self) -> dict:
        by_rule: dict = {}
        by_family: dict = {}
        for f in self.findings:
            key = "suppressed" if f.suppressed else "open"
            by_rule.setdefault(f.rule, {"open": 0, "suppressed": 0})
            by_rule[f.rule][key] += 1
            by_family.setdefault(f.family, {"open": 0, "suppressed": 0})
            by_family[f.family][key] += 1
        return {"rules": by_rule, "families": by_family}

    def to_json(self) -> str:
        return json.dumps({
            "tool": "plane-lint",
            "version": 1,
            "files": self.files,
            "findings": [f.to_dict() for f in self.findings],
            "counts": self.counts(),
            "open": len(self.unsuppressed),
            "suppressed": len(self.suppressed),
            "parse_errors": self.errors,
        }, indent=2, sort_keys=True)

    def render(self) -> str:
        lines = [f.render() for f in sorted(
            self.findings, key=lambda f: (f.path, f.line, f.rule))]
        counts = self.counts()["families"]
        fam = ", ".join(f"{name}: {c['open']}+{c['suppressed']}a"
                        for name, c in sorted(counts.items()))
        lines.append(
            f"plane-lint: {len(self.unsuppressed)} finding(s), "
            f"{len(self.suppressed)} allowed, {self.files} file(s)"
            + (f" [{fam}]" if fam else ""))
        for path, err in self.errors:
            lines.append(f"plane-lint: parse error in {path}: {err}")
        return "\n".join(lines)


def iter_py_files(paths) -> list:
    out = []
    for path in paths:
        if os.path.isfile(path) and path.endswith(".py"):
            out.append(path)
        elif os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__",))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
    return out


def _relpath(path: str) -> str:
    rel = os.path.relpath(path)
    return rel.replace(os.sep, "/")


def lint_paths(paths, config: LintConfig = DEFAULT_CONFIG) -> LintResult:
    result = LintResult()
    contexts = []
    for path in iter_py_files(paths):
        rel = _relpath(path)
        try:
            with open(path, encoding="utf-8") as fh:
                src = fh.read()
            ctx = ModuleContext(rel, src)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            result.errors.append((rel, str(exc)))
            continue
        contexts.append(ctx)
    result.files = len(contexts)

    lock_infos = []
    by_rel = {}
    for ctx in contexts:
        by_rel[ctx.relpath] = ctx
        for rule in _PER_MODULE_RULES:
            result.findings.extend(rule(ctx, config))
        result.findings.extend(ctx.meta_findings())
        lock_infos.append(rule_locks.collect(ctx, config))

    # cross-module lock-order pass (suppressible at the acquisition line)
    for f in rule_locks.finalize(lock_infos, config):
        ctx = by_rel.get(f.path)
        if ctx is not None:
            for line in (f.line - 1, f.line):
                for rid, reason in ctx.suppressions.get(line, ()):
                    if rid == f.rule and reason:
                        f.suppressed = True
                        f.suppress_reason = reason
        result.findings.append(f)
    return result


def lock_graph_for(paths, config: LintConfig = DEFAULT_CONFIG):
    """(edges, ranks) of the static lock-acquisition graph — the runtime
    watchdog (elasticsearch_tpu.analysis.watchdog) consumes this."""
    infos = []
    for path in iter_py_files(paths):
        try:
            with open(path, encoding="utf-8") as fh:
                ctx = ModuleContext(_relpath(path), fh.read())
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
        infos.append(rule_locks.collect(ctx, config))
    edges = rule_locks.lock_graph(infos, config)
    return edges, rule_locks.lock_ranks(edges)
