"""fallback-taxonomy: one closed reason vocabulary per lane.

Every ``note_*_fallback`` / decline call's reason string must come from
the lane's registered vocabulary
(``elasticsearch_tpu.search.lanes.LANE_REASONS``):

* ``fallback-unknown-reason`` — a literal reason not in the lane's
  vocabulary (a typo forks the taxonomy: dashboards, slowlog labels
  and the lane-graph artifact all disagree);
* ``fallback-unresolved-reason`` — a reason the analyzer cannot pin to
  literals (and that is not a noter-wrapper's forwarded parameter):
  dynamic reasons bypass the closed vocabulary entirely;
* ``fallback-duplicate-reason`` — the registry lists the same reason
  twice within one lane;
* ``fallback-unused-reason`` — a registered reason no call site ever
  notes (emitted only when the program actually contains call sites
  for that lane, so linting the registry file alone stays quiet).

The same reason-site extraction feeds ``--emit-lane-graph``
(:mod:`elasticsearch_tpu.analysis.lint.lane_graph`), which records each
lane's vocabulary WITH the file:line of every decline site — the
machine-readable half of this rule.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, last_name)
from elasticsearch_tpu.analysis.lint.program import (
    const_of, literal_assignment)

#: noter name → 0-based positional index of the reason argument; None
#: means keyword-only (``reason=...``) — a call without the keyword
#: notes no reason and is skipped.
_REASON_ARG = {"note_plane_fallback": 0, "_note_plane_fallback": 1,
               "note_fallback": None, "note_impact_fallback": 0,
               "note_knn_fallback": 0, "note_percolate_fallback": 0,
               "note_scheduler_shed": 0, "note_planner_fallback": 0}


def lane_registry(program, cfg) -> "tuple | None":
    """((lane → reasons tuple), registry ctx, {lane → key lineno}) from
    the lane-registry module's literal AST, or None when absent."""
    for ctx in program.registry_contexts(cfg.lane_registry_modules):
        value = literal_assignment(ctx.tree, cfg.lane_reasons_name)
        if not isinstance(value, ast.Dict):
            continue
        try:
            reasons = const_of(value)
        except ValueError:
            continue
        lines = {k.value: k.lineno for k in value.keys
                 if isinstance(k, ast.Constant)}
        return reasons, ctx, lines
    return None


def _reason_expr(call: ast.Call, noter: str):
    """The reason argument's AST, or None when the call notes none."""
    for kw in call.keywords:
        if kw.arg == "reason":
            return kw.value
    idx = _REASON_ARG.get(noter)
    if idx is not None and len(call.args) > idx:
        return call.args[idx]
    return None


def _literal_reasons(ctx, fn_node, expr) -> "list | None":
    if isinstance(expr, ast.Constant):
        return [expr.value] if isinstance(expr.value, str) else None
    if isinstance(expr, ast.IfExp):
        a = _literal_reasons(ctx, fn_node, expr.body)
        b = _literal_reasons(ctx, fn_node, expr.orelse)
        return a + b if a is not None and b is not None else None
    if isinstance(expr, ast.Name) and fn_node is not None:
        bound = None
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in n.targets):
                bound = n.value
        if bound is not None:
            return _literal_reasons(ctx, fn_node, bound)
    return None


def iter_reason_sites(program, cfg):
    """Yield (lane, reasons | None, ctx, call node) for every noter
    call with a reason argument; ``reasons`` is None when not statically
    resolvable (a forwarded noter-wrapper parameter yields nothing —
    its literals appear at the wrapper's own call sites)."""
    noters = dict(cfg.fallback_noters)
    for ctx in program.contexts:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = last_name(node.func)
            lane = noters.get(name)
            if lane is None:
                continue
            fn = ctx.enclosing_function(node)
            if fn is not None and fn.name in noters:
                continue                  # wrapper body forwards its param
            expr = _reason_expr(node, name)
            if expr is None:
                continue                  # notes no reason (note_fallback(e))
            reasons = _literal_reasons(
                ctx, fn.node if fn is not None else None, expr)
            yield lane, reasons, ctx, node


def check_program(program, cfg) -> list:
    hit = lane_registry(program, cfg)
    if hit is None:
        return []
    vocab, reg_ctx, reg_lines = hit

    by_ctx: dict = {}

    def report(ctx, rule, node_or_line, message):
        _, findings, nodes = by_ctx.setdefault(ctx.relpath, (ctx, [], []))
        line = node_or_line if isinstance(node_or_line, int) \
            else node_or_line.lineno
        findings.append(Finding(rule, ctx.relpath, line, message))
        nodes.append(None if isinstance(node_or_line, int)
                     else node_or_line)

    # registry self-checks: duplicates within a lane
    for lane, reasons in sorted(vocab.items()):
        seen = set()
        for r in reasons:
            if r in seen:
                report(reg_ctx, "fallback-duplicate-reason",
                       reg_lines.get(lane, 1),
                       f"reason [{r}] is registered twice in the "
                       f"[{lane}] lane vocabulary")
            seen.add(r)

    used: dict = {lane: set() for lane in vocab}
    lanes_with_sites: set = set()
    for lane, reasons, ctx, node in iter_reason_sites(program, cfg):
        lanes_with_sites.add(lane)
        if reasons is None:
            report(ctx, "fallback-unresolved-reason", node,
                   f"[{lane}]-lane fallback reason is not statically "
                   f"resolvable — use a string literal (or a "
                   f"conditional of literals) so the closed vocabulary "
                   f"holds")
            continue
        for r in reasons:
            used.setdefault(lane, set()).add(r)
            if r not in vocab.get(lane, ()):
                report(ctx, "fallback-unknown-reason", node,
                       f"[{r}] is not in the registered [{lane}]-lane "
                       f"vocabulary — add it to lanes.LANE_REASONS"
                       f"[{lane!r}] (or fix the typo: the taxonomy is "
                       f"closed)")

    for lane, reasons in sorted(vocab.items()):
        if lane not in lanes_with_sites:
            continue                      # lane code not in the linted set
        for r in reasons:
            if r not in used.get(lane, ()):
                report(reg_ctx, "fallback-unused-reason",
                       reg_lines.get(lane, 1),
                       f"registered [{lane}]-lane reason [{r}] is "
                       f"never noted by any call site — dead "
                       f"vocabulary misleads the lane graph")

    out = []
    for ctx, findings, nodes in by_ctx.values():
        anchored = [(f, n) for f, n in zip(findings, nodes)
                    if n is not None]
        line_only = [f for f, n in zip(findings, nodes) if n is None]
        out.extend(apply_suppressions(
            ctx, [f for f, _ in anchored], [n for _, n in anchored]))
        for f in line_only:
            for ln in (f.line - 1, f.line):
                for rid, reason in ctx.suppressions.get(ln, ()):
                    if rid == f.rule and reason:
                        ctx.used_suppressions.add((ln, rid))
                        f.suppressed, f.suppress_reason = True, reason
            out.append(f)
    return out
