"""Whole-program plumbing for plane-lint v2.

One :class:`ProgramIndex` per lint run: every module's
:class:`~elasticsearch_tpu.analysis.lint.context.ModuleContext` plus the
project-wide symbol table and call graph the interprocedural rule
families walk —

* **module table** — dotted modkey → context, with suffix matching so
  ``from elasticsearch_tpu.search import jit_exec`` resolves no matter
  what working directory the relpaths were computed from;
* **function table** — fully-qualified name (``modkey.Qual.name``) →
  (context, FunctionInfo), covering nested defs and methods;
* **call graph** — resolved edges for: bare names through the lexical
  scope chain, ``from``-imported functions, ``module.fn`` attribute
  calls, ``self.method`` / singleton / constructor-inferred receivers
  (``x = ClassName(...)`` then ``x.method()`` — the known seam classes
  resolve this way), and ``self.attr.method()`` through ``__init__``
  attribute types;
* **trace regions** — functions staged by ``seam_jit`` / ``jax.jit`` /
  ``vmap`` / ``lax.scan`` / ``lax.map`` (decorated, passed by name,
  inside a ``partial``, or called from a staged lambda), closed over
  the call graph. ``trace_parents`` keeps BFS back-pointers so a
  finding can print the call path from the staged seed to the impure
  statement.

Resolution is deliberately CONSERVATIVE-precise: a callee that cannot
be statically pinned (dynamic dispatch, foreign libraries) resolves to
nothing rather than to every same-named function — interprocedural
rules prefer a missed edge over a storm of false positives.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from elasticsearch_tpu.analysis.lint.context import (
    dotted, last_name, module_matches)


def modkey_for(relpath: str) -> str:
    return relpath.replace("\\", "/").rsplit(".py", 1)[0].replace("/", ".")


@dataclass
class _ModuleInfo:
    ctx: object
    modkey: str
    #: top-level bound names (module globals)
    module_names: set = field(default_factory=set)
    #: module-level singleton name → class name
    singletons: dict = field(default_factory=dict)
    #: module-level function name → fqn
    top_functions: dict = field(default_factory=dict)


class ProgramIndex:
    def __init__(self, contexts: list, cfg):
        self.cfg = cfg
        self.contexts = list(contexts)
        self.modules: dict[str, _ModuleInfo] = {}
        self.functions: dict = {}          # fqn → (ctx, FunctionInfo)
        self._fqn_of_info: dict = {}       # id(info) → fqn
        self.methods: dict = {}            # (class, name) → [fqn]
        self.class_attr_types: dict = {}   # (class, attr) → class name
        self.calls: dict = {}              # fqn → [(Call node, set(fqns))]
        self.call_graph: dict = {}         # fqn → set(fqns)
        self._local_ctor_vars: dict = {}   # fqn → {var → class name}
        self._build_tables()
        self._build_call_graph()
        self._traced: "tuple | None" = None

    # ------------------------------------------------------------------ #
    # symbol tables
    # ------------------------------------------------------------------ #

    def _build_tables(self) -> None:
        for ctx in self.contexts:
            mod = _ModuleInfo(ctx, modkey_for(ctx.relpath))
            self.modules[mod.modkey] = mod
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign):
                    mod.module_names.update(
                        t.id for t in node.targets
                        if isinstance(t, ast.Name))
                    if isinstance(node.value, ast.Call):
                        ctor = last_name(node.value.func)
                        if ctor and ctor[0].isupper():
                            for t in node.targets:
                                if isinstance(t, ast.Name):
                                    mod.singletons[t.id] = ctor
                elif isinstance(node, ast.AnnAssign) and \
                        isinstance(node.target, ast.Name):
                    mod.module_names.add(node.target.id)
            for info in ctx.functions:
                fqn = f"{mod.modkey}.{info.qualname}"
                self.functions[fqn] = (ctx, info)
                self._fqn_of_info[id(info)] = fqn
                if info.parent is None and info.class_name is None:
                    mod.top_functions[info.name] = fqn
                if info.class_name is not None and info.parent is None:
                    self.methods.setdefault(
                        (info.class_name, info.name), []).append(fqn)
                # constructor-typed locals: `v = ClassName(...)`
                locals_: dict = {}
                for n in ast.walk(info.node):
                    if isinstance(n, ast.Assign) and \
                            isinstance(n.value, ast.Call):
                        ctor = last_name(n.value.func)
                        if ctor and ctor[0].isupper():
                            for t in n.targets:
                                if isinstance(t, ast.Name):
                                    locals_[t.id] = ctor
                                elif isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) and \
                                        t.value.id == "self" and \
                                        info.class_name:
                                    self.class_attr_types[
                                        (info.class_name, t.attr)] = ctor
                self._local_ctor_vars[fqn] = locals_

    def fqn(self, info) -> str | None:
        return self._fqn_of_info.get(id(info))

    def resolve_module(self, dotted_path: str) -> "_ModuleInfo | None":
        """Module by dotted import path, suffix-matched against the
        relpath-derived modkeys."""
        hit = self.modules.get(dotted_path)
        if hit is not None:
            return hit
        want = "." + dotted_path
        for key, mod in self.modules.items():
            if key.endswith(want):
                return mod
        return None

    # ------------------------------------------------------------------ #
    # callee resolution
    # ------------------------------------------------------------------ #

    def resolve_callable(self, ctx, expr, caller_info) -> set:
        """fqns of function DEFINITIONS the Name/Attribute `expr` may
        refer to (empty when not statically resolvable)."""
        mod = self.modules.get(modkey_for(ctx.relpath))
        if mod is None:
            return set()
        if isinstance(expr, ast.Name):
            return self._resolve_bare(ctx, mod, expr.id, caller_info)
        if isinstance(expr, ast.Attribute):
            return self._resolve_attr(ctx, mod, expr, caller_info)
        return set()

    def _resolve_bare(self, ctx, mod, name: str, caller_info) -> set:
        # innermost nested def in the lexical chain wins
        info = caller_info
        while info is not None:
            cand = f"{mod.modkey}.{info.qualname}.{name}"
            if cand in self.functions:
                return {cand}
            info = info.parent
        if name in mod.top_functions:
            return {mod.top_functions[name]}
        target = ctx.import_aliases.get(name)
        if target is not None:
            # from pkg.mod import fn  (alias → "pkg.mod.fn")
            head, _, attr = target.rpartition(".")
            tmod = self.resolve_module(head)
            if tmod is not None and attr in tmod.top_functions:
                return {tmod.top_functions[attr]}
        return set()

    def _resolve_attr(self, ctx, mod, expr: ast.Attribute,
                      caller_info) -> set:
        base, attr = expr.value, expr.attr
        if isinstance(base, ast.Name):
            if base.id == "self" and caller_info is not None and \
                    caller_info.class_name:
                return set(self.methods.get(
                    (caller_info.class_name, attr), ()))
            cls = mod.singletons.get(base.id)
            if cls is None and caller_info is not None:
                fqn = self.fqn(caller_info)
                cls = self._local_ctor_vars.get(fqn, {}).get(base.id)
            if cls is not None:
                return set(self.methods.get((cls, attr), ()))
            target = ctx.import_aliases.get(base.id)
            if target is not None:
                tmod = self.resolve_module(target)
                if tmod is not None and attr in tmod.top_functions:
                    return {tmod.top_functions[attr]}
        elif isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and caller_info is not None and \
                caller_info.class_name:
            cls = self.class_attr_types.get(
                (caller_info.class_name, base.attr))
            if cls is not None:
                return set(self.methods.get((cls, attr), ()))
        return set()

    def _build_call_graph(self) -> None:
        for ctx in self.contexts:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                caller = ctx.enclosing_function(node)
                if caller is None:
                    continue
                fqn = self.fqn(caller)
                if fqn is None:
                    continue
                targets = self.resolve_callable(ctx, node.func, caller)
                self.calls.setdefault(fqn, []).append((node, targets))
                if targets:
                    self.call_graph.setdefault(fqn, set()).update(targets)

    def reachable_from(self, seeds: set) -> set:
        out = set(seeds)
        stack = list(seeds)
        while stack:
            cur = stack.pop()
            for nxt in self.call_graph.get(cur, ()):
                if nxt not in out:
                    out.add(nxt)
                    stack.append(nxt)
        return out

    def transitive_marked(self, direct: set) -> set:
        """Functions that reach (call, transitively) any of `direct` —
        the reverse closure, for "does this callee eventually X" rules
        like release-reachability and host-sync."""
        rev: dict = {}
        for src, dsts in self.call_graph.items():
            for d in dsts:
                rev.setdefault(d, set()).add(src)
        out = set(direct)
        stack = list(direct)
        while stack:
            cur = stack.pop()
            for prev in rev.get(cur, ()):
                if prev not in out:
                    out.add(prev)
                    stack.append(prev)
        return out

    # ------------------------------------------------------------------ #
    # trace regions
    # ------------------------------------------------------------------ #

    def _is_stager(self, call: ast.Call) -> bool:
        cfg = self.cfg
        if last_name(call.func) in cfg.trace_stagers:
            return True
        d = dotted(call.func)
        return bool(d) and any(d == s or d.endswith("." + s)
                               for s in cfg.trace_stagers_dotted)

    def _staged_refs(self, ctx, arg, scope_info) -> set:
        """Function fqns a stager ARGUMENT stages: a direct
        Name/Attribute reference, names called from a lambda body, or
        (one level) the arguments of a ``partial(...)`` wrapper."""
        out: set = set()
        if isinstance(arg, (ast.Name, ast.Attribute)):
            out |= self.resolve_callable(ctx, arg, scope_info)
        elif isinstance(arg, ast.Lambda):
            for n in ast.walk(arg.body):
                if isinstance(n, ast.Call):
                    out |= self.resolve_callable(ctx, n.func, scope_info)
        elif isinstance(arg, ast.Call) and \
                last_name(arg.func) == "partial":
            for sub in list(arg.args) + [kw.value for kw in arg.keywords]:
                out |= self._staged_refs(ctx, sub, scope_info)
        return out

    def traced(self) -> "tuple[set, dict]":
        """(trace-reachable fqns, BFS back-pointers). Seeds are staged
        functions; the closure follows the call graph — everything in
        the set runs at TRACE time (with tracers in scope), so the
        trace-purity rule polices its statements."""
        if self._traced is not None:
            return self._traced
        seeds: dict = {}                  # fqn → (relpath, line) of stage site
        for ctx in self.contexts:
            for info in ctx.functions:
                for dec in info.node.decorator_list:
                    d = ast.dump(dec)
                    if any(f"'{s}'" in d for s in self.cfg.trace_stagers):
                        fqn = self.fqn(info)
                        seeds.setdefault(
                            fqn, (ctx.relpath, info.node.lineno))
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call) or \
                        not self._is_stager(node):
                    continue
                scope = ctx.enclosing_function(node)
                for arg in list(node.args) + \
                        [kw.value for kw in node.keywords]:
                    for fqn in self._staged_refs(ctx, arg, scope):
                        seeds.setdefault(fqn, (ctx.relpath, node.lineno))
        parents: dict = {fqn: None for fqn in seeds}
        queue = sorted(seeds)
        reached = set(seeds)
        while queue:
            nxt_queue = []
            for cur in queue:
                for nxt in sorted(self.call_graph.get(cur, ())):
                    if nxt not in reached:
                        reached.add(nxt)
                        parents[nxt] = cur
                        nxt_queue.append(nxt)
            queue = nxt_queue
        self._traced = (reached, parents)
        return self._traced

    def trace_path(self, fqn: str) -> str:
        """"seed → … → fqn" rendered from the BFS back-pointers."""
        _, parents = self.traced()
        chain = [fqn]
        seen = {fqn}
        while parents.get(chain[0]) is not None and \
                parents[chain[0]] not in seen:
            chain.insert(0, parents[chain[0]])
            seen.add(chain[0])
        return " → ".join(short_fqn(c) for c in chain)

    # ------------------------------------------------------------------ #
    # registry-module helpers (counter / fallback / lane-graph rules)
    # ------------------------------------------------------------------ #

    def registry_contexts(self, patterns: tuple) -> list:
        return [ctx for ctx in self.contexts
                if module_matches(ctx.relpath, patterns)]


def short_fqn(fqn: str) -> str:
    """Drop the package prefix for readable messages: keep the module's
    last component plus the qualname tail."""
    parts = fqn.split(".")
    return ".".join(parts[-3:]) if len(parts) > 3 else fqn


def literal_dict_keys(tree: ast.Module, name: str) -> "list | None":
    """Keys of a module-level ``NAME = {literal dict}`` assignment (the
    registry-parsing primitive), or None when absent."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            if isinstance(node.value, ast.Dict):
                return [k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)]
    return None


def literal_assignment(tree: ast.Module, name: str):
    """The value AST of a module-level ``NAME = ...`` assignment."""
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == name
                for t in node.targets):
            return node.value
    return None


def const_of(node):
    """Python value of a literal AST (constants, tuples, lists, dicts of
    literals) — the registry dicts are plain literals by contract."""
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(const_of(e) for e in node.elts)
    if isinstance(node, ast.Dict):
        return {const_of(k): const_of(v)
                for k, v in zip(node.keys, node.values)}
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left, right = const_of(node.left), const_of(node.right)
        if isinstance(left, str) and isinstance(right, str):
            return left + right
    raise ValueError(f"not a literal: {ast.dump(node)[:80]}")
