"""recompile-hazard: request-path code must not construct programs.

``recompile-request-path``: a ``jax.jit`` / ``seam_jit`` / ``jax.vmap``
call inside a function body re-traces per invocation unless it is:

* inside a TRACED context — the enclosing function is (transitively)
  staged by ``jax.jit`` / ``jax.vmap`` / ``shard_map`` (decorated,
  passed by name, or called from a traced function: trace-time code
  runs once per compile, not per request);
* a closure handed to the ``_get_compiled`` trampoline, or in a
  function that consults the PROGRAM-layer cache (``_get_compiled`` /
  ``_program_cache`` / ``note_mesh_program`` references);
* a BUILDER — a function that directly returns the constructed program
  — whose call sites are memoized (``cache[k] = build(...)`` under a
  ``k not in cache`` guard) or module-level.

``recompile-unbucketed-key``: a program-cache key tuple (flowing into
``_get_compiled`` or a ``*_cache`` subscript) carrying a raw ``len(...)``
component — batch sizes must pass through ``pow2_bucket`` (or another
``bucket_fns`` entry) so varying request counts share programs.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, dotted, last_name)

_STAGERS = ("jit", "vmap", "shard_map", "shard_map_compat", "pmap",
            "seam_jit")


def _is_stage_call(node: ast.Call) -> bool:
    return last_name(node.func) in _STAGERS


def _traced_functions(ctx) -> set:
    """qualnames of functions that run at TRACE time: passed by name to
    a stager, decorated by one, or (fixpoint) called from a traced
    function in this module."""
    by_name: dict = {}
    for fn in ctx.functions:
        by_name.setdefault(fn.name, []).append(fn)
    traced: set = set()
    # seed: decorator or passed-by-name-to-stager
    for fn in ctx.functions:
        for dec in fn.node.decorator_list:
            d = ast.dump(dec)           # covers @jax.jit and
            if any(f"'{s}'" in d for s in _STAGERS):   # @partial(jax.jit, ...)
                traced.add(fn.qualname)
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call) and _is_stage_call(node):
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        for fn in by_name.get(sub.id, ()):
                            traced.add(fn.qualname)
    # nested defs inside traced functions execute at trace time too
    def _close_nested():
        added = False
        for fn in ctx.functions:
            if fn.qualname in traced:
                continue
            if fn.parent is not None and fn.parent.qualname in traced:
                traced.add(fn.qualname)
                added = True
        return added
    # fixpoint: callees of traced functions are traced
    changed = True
    while changed:
        changed = _close_nested()
        for fn in ctx.functions:
            if fn.qualname not in traced:
                continue
            for n in ast.walk(fn.node):
                if isinstance(n, ast.Call):
                    callee = last_name(n.func)
                    for cand in by_name.get(callee, ()):
                        if cand.qualname not in traced:
                            traced.add(cand.qualname)
                            changed = True
    return traced


def _consults_cache(ctx, cfg, fn) -> bool:
    info = fn
    while info is not None:
        for n in ast.walk(info.node):
            if isinstance(n, (ast.Name, ast.Attribute)) and \
                    last_name(n) in cfg.cache_markers:
                return True
        info = info.parent
    return False


def _in_trampoline(ctx, cfg, fn) -> bool:
    info = fn
    while info is not None:
        outer = info.parent
        scope = outer.node if outer is not None else ctx.tree
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and \
                    last_name(n.func) in cfg.trampolines:
                if any(isinstance(a, ast.Name) and a.id == info.name
                       for a in n.args):
                    return True
        info = outer
    return False


def _builders(ctx, cfg) -> set:
    """Functions whose return value IS a constructed program (directly
    `return jax.jit(...)` / `return seam_jit(...)`), closed over
    functions returning a builder's result."""
    names: set = set()
    changed = True
    while changed:
        changed = False
        for fn in ctx.functions:
            if fn.name in names:
                continue
            for n in ast.walk(fn.node):
                if isinstance(n, ast.Return) and \
                        isinstance(n.value, ast.Call):
                    callee = last_name(n.value.func)
                    if dotted(n.value.func) in cfg.jit_constructors or \
                            callee in {c.rsplit(".", 1)[-1]
                                       for c in cfg.jit_constructors} or \
                            callee in names:
                        names.add(fn.name)
                        changed = True
    return names


def _is_memo_site(ctx, call: ast.Call) -> bool:
    """`cache[k] = build(...)` guarded by a `k not in cache` test (the
    memo idiom), or any subscript-store into a *cache*-named container."""
    stmt = ctx.enclosing_stmt(call)
    if isinstance(stmt, ast.Assign) and any(
            isinstance(t, ast.Subscript) for t in stmt.targets):
        for anc in ctx.ancestors(stmt):
            if isinstance(anc, ast.If):
                t = anc.test
                if isinstance(t, ast.Compare) and any(
                        isinstance(op, (ast.NotIn, ast.Is, ast.Eq))
                        for op in t.ops):
                    return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
    return False


def check(ctx, cfg, program=None) -> list:
    findings, nodes = [], []
    traced = _traced_functions(ctx)
    builders = _builders(ctx, cfg)
    ctor_lasts = {c.rsplit(".", 1)[-1] for c in cfg.jit_constructors}

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee_last = last_name(node.func)
        is_ctor = (dotted(node.func) in cfg.jit_constructors or
                   callee_last in ctor_lasts)
        is_vmap = callee_last in ("vmap",)
        is_builder_call = callee_last in builders
        if not (is_ctor or is_vmap or is_builder_call):
            continue
        fn = ctx.enclosing_function(node)
        if fn is None:
            continue                    # module-level kernel definition
        if fn.qualname in traced or \
                any(i.qualname in traced for i in ctx.enclosing_chain(node)):
            continue
        if _in_trampoline(ctx, cfg, fn) or _consults_cache(ctx, cfg, fn):
            continue
        if fn.name in builders:
            continue                    # construction is the builder's job
        if _is_memo_site(ctx, node):
            continue                    # memoized construction
        what = "jax.vmap" if is_vmap else (dotted(node.func) or callee_last)
        findings.append(Finding(
            "recompile-request-path", ctx.relpath, node.lineno,
            f"{what} constructed inside {fn.qualname}() re-traces per "
            f"call — route through the PROGRAM-layer cache "
            f"(_get_compiled / _program_cache) or memoize the builder"))
        nodes.append(node)

    # --- unbucketed key components ---------------------------------------
    for fn in ctx.functions:
        bucketed = _bucketed_names(fn.node, cfg)
        for call in ast.walk(fn.node):
            if not isinstance(call, ast.Call) or \
                    last_name(call.func) not in cfg.trampolines:
                continue
            if not call.args:
                continue
            key = _resolve_key_expr(fn.node, call.args[0])
            for el in _tuple_elements(key):
                bad = _raw_len(el, bucketed)
                if bad is not None:
                    findings.append(Finding(
                        "recompile-unbucketed-key", ctx.relpath,
                        bad.lineno,
                        f"program-cache key in {fn.qualname}() carries "
                        f"a raw len(...) component — bucket it with "
                        f"{'/'.join(cfg.bucket_fns)} so varying batch "
                        f"sizes share compiled programs"))
                    nodes.append(bad)
    return apply_suppressions(ctx, findings, nodes)


def _bucketed_names(fn_node, cfg) -> set:
    out = set()
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and last_name(n.value.func) in cfg.bucket_fns:
            out.update(t.id for t in n.targets
                       if isinstance(t, ast.Name))
    return out


def _resolve_key_expr(fn_node, expr):
    """Follow one level of `key = (...)` indirection."""
    if isinstance(expr, ast.Name):
        for n in ast.walk(fn_node):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == expr.id
                    for t in n.targets):
                return n.value
    return expr


def _tuple_elements(expr):
    if isinstance(expr, ast.Tuple):
        return list(expr.elts)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _tuple_elements(expr.left) + _tuple_elements(expr.right)
    return []


def _raw_len(el, bucketed: set):
    """A len(...) call (or int(len(...))) not routed through a bucket
    fn, or a name bound from one."""
    if isinstance(el, ast.Call) and last_name(el.func) == "int" and \
            el.args:
        el = el.args[0]
    if isinstance(el, ast.Call) and last_name(el.func) == "len":
        return el
    if isinstance(el, ast.Name) and el.id.startswith("len_") and \
            el.id not in bucketed:
        return el
    return None
