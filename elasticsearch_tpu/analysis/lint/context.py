"""Shared AST plumbing for plane-lint.

One :class:`ModuleContext` per analyzed file: the parsed tree with parent
links, a function index (qualnames, lexical nesting, owning class), the
import-alias table (so ``jit_exec.device_fault_point`` resolves across
modules), and the inline-suppression index for the
``# estpu: allow[rule-id] <reason>`` syntax.

Suppressions attach to the STATEMENT they share a line with (any line of
a multi-line statement works) or to the line directly above it; a bare
``allow`` with no reason string does not suppress — it surfaces as an
``allow-missing-reason`` finding instead, so every surviving suppression
documents why the invariant does not apply.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from dataclasses import dataclass, field

_SUPPRESS_RE = re.compile(
    r"#\s*estpu:\s*allow\[([A-Za-z0-9_-]+)\]\s*(.*?)\s*$")

#: rule-id → family (the JSON report counts by family; the ids are what
#: suppressions name)
RULE_FAMILIES = {
    "breaker-unreleased": "breaker-discipline",
    "breaker-double-release": "breaker-discipline",
    "device-raw-call": "device-seam",
    "device-unguarded": "device-seam",
    "device-unknown-site": "device-seam",
    "recompile-request-path": "recompile-hazard",
    "recompile-unbucketed-key": "recompile-hazard",
    "lock-order": "lock-discipline",
    "lock-unguarded-state": "lock-discipline",
    "host-sync-hot-loop": "host-sync",
    "span-unscoped-site": "span-discipline",
    "span-unended": "span-discipline",
    # trace-purity: nothing reachable from inside a traced body may
    # touch host state (the PR 10 trace-time-import bug class)
    "trace-impure-import": "trace-purity",
    "trace-impure-global": "trace-purity",
    "trace-impure-state-write": "trace-purity",
    "trace-impure-call": "trace-purity",
    "trace-impure-capture": "trace-purity",
    # counter-discipline: every bump registered, every registered key
    # bumped, every store surfaced from the registry, every registry
    # reachable from the /_prometheus exposition
    "counter-unregistered": "counter-discipline",
    "counter-unbumped": "counter-discipline",
    "counter-unsurfaced": "counter-discipline",
    "counter-unexported": "counter-discipline",
    # fallback-taxonomy: one closed reason vocabulary per lane
    "fallback-unknown-reason": "fallback-taxonomy",
    "fallback-duplicate-reason": "fallback-taxonomy",
    "fallback-unused-reason": "fallback-taxonomy",
    "fallback-unresolved-reason": "fallback-taxonomy",
    # program-cost-discipline: every program compile flows through the
    # observed_compile seam (so the cost observatory sees it), under a
    # registered program-lane literal
    "program-cost-unobserved": "program-cost-discipline",
    "program-cost-unknown-lane": "program-cost-discipline",
    # unbounded-wait: every blocking wait on the serving path carries a
    # timeout (a wedged dispatch must become a typed failover, never a
    # hung request — the stall-tolerance ladder's static half)
    "unbounded-wait": "unbounded-wait",
    # plan-node-spans: every planner-emitted plan node opens a literal
    # ``plan.*`` span and carries a registered planner fallback reason
    # (the cost-driven planner's observability contract)
    "plan-node-unspanned": "plan-node-spans",
    "plan-node-unregistered-reason": "plan-node-spans",
    "allow-missing-reason": "meta",
    "allow-stale": "meta",
}


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str
    suppressed: bool = False
    suppress_reason: str | None = None
    #: warning-tier findings (the stale-suppression audit) are reported
    #: but do not fail the gate unless --strict-suppressions promotes
    #: them
    warning: bool = False

    @property
    def family(self) -> str:
        return RULE_FAMILIES.get(self.rule, "unknown")

    def to_dict(self) -> dict:
        return {"rule": self.rule, "family": self.family,
                "path": self.path, "line": self.line,
                "message": self.message, "suppressed": self.suppressed,
                "suppress_reason": self.suppress_reason,
                "warning": self.warning}

    def render(self) -> str:
        tag = "allowed" if self.suppressed else \
            ("warning" if self.warning else "error")
        out = (f"{self.path}:{self.line}: [{self.rule}] {tag}: "
               f"{self.message}")
        if self.suppressed and self.suppress_reason:
            out += f" (reason: {self.suppress_reason})"
        return out


@dataclass
class LintConfig:
    """Everything repo-specific the rules key on — overridable so the
    fixture suite can point the seam/hot-path scoping at synthetic
    files."""

    #: modules allowed to touch the device directly (fnmatch over the
    #: posix relpath) — the seam allowlist from the device-seam rule
    seam_modules: tuple = ("*/search/jit_exec.py",
                           "*/parallel/mesh_engine.py",
                           "*/parallel/mesh.py",
                           "*/ops/*.py")
    #: modules whose dispatch loops the host-sync rule polices
    hot_modules: tuple = ("*/search/jit_exec.py",
                          "*/parallel/mesh_engine.py",
                          "*/search/percolator.py",
                          "*/ops/percolate.py")
    #: the site classes device_fault_point may name
    #: (testing_disruption.DEVICE_FAULT_SITES + READER_UPLOAD_SITE;
    #: impact-upload / blockmax-compose / pruning-dispatch are the
    #: impact-ordered lane's device touchpoints)
    known_sites: tuple = ("dispatch", "compile", "upload", "compose",
                          "plane-dispatch", "percolate", "reader-upload",
                          "impact-upload", "blockmax-compose",
                          "pruning-dispatch",
                          # dense/late-interaction lane: vector block
                          # upload, fused MaxSim + hybrid-fusion
                          # dispatches
                          "vector-upload", "maxsim-dispatch",
                          "fusion-dispatch",
                          # the planner's composed impact→rescore arm
                          "rescore-dispatch",
                          # mesh-sharded retrieval lanes: placed block
                          # upload, pod-slice impact sweep dispatch,
                          # cross-chip knn candidate merge dispatch
                          "block-placement-upload",
                          "impact-shard-dispatch", "knn-mesh-merge")
    #: site classes that mark a LOOP as a dispatch loop (host-sync rule)
    dispatch_sites: tuple = ("dispatch", "plane-dispatch", "percolate",
                             "pruning-dispatch", "maxsim-dispatch",
                             "fusion-dispatch", "rescore-dispatch",
                             "impact-shard-dispatch", "knn-mesh-merge")
    #: site classes that dominate a raw ``jax.device_put`` inside a seam
    #: module (the upload/compose family of device touchpoints)
    upload_sites: tuple = ("upload", "compose", "reader-upload",
                           "impact-upload", "blockmax-compose",
                           "vector-upload", "block-placement-upload")
    #: the seam entry points (calls routed through these are guarded)
    fault_point_names: tuple = ("device_fault_point",)
    seam_wrappers: tuple = ("seam_device_put", "seam_jit")
    #: span constructors the span-discipline rule pairs with fault
    #: points (and requires to be used as `with` contexts)
    span_fns: tuple = ("device_span",)
    #: modules exempt from span-discipline (the tracer's own home —
    #: constructors are DEFINED there, not leaked)
    span_exempt_modules: tuple = ("*/observability/*",)
    #: closures passed (by name) to these functions are compiled behind
    #: a guarded, cache-keyed trampoline (observed_compile owns the
    #: fault point + cost-table stamp for the lowered program it
    #: receives)
    trampolines: tuple = ("_get_compiled", "observed_compile")
    #: referencing any of these inside a function counts as consulting
    #: the PROGRAM-layer cache (recompile rule)
    cache_markers: tuple = ("_get_compiled", "_program_cache",
                            "note_mesh_program")
    #: calls that construct a compiled program (recompile rule tracks
    #: raw jax.jit plus the repo's guarded wrapper)
    jit_constructors: tuple = ("jax.jit", "seam_jit")
    #: batch-size bucketing helpers (recompile key rule)
    bucket_fns: tuple = ("pow2_bucket",)
    #: charge constructors the breaker rule pairs with .release()
    charge_classes: tuple = ("OneShotCharge",)
    #: methods whose callers are asserted (by name) to hold the lock
    locked_suffix: str = "_locked"
    #: container methods that mutate in place (lock-discipline rule)
    mutators: tuple = ("append", "add", "update", "clear", "pop",
                       "popitem", "setdefault", "extend", "remove",
                       "discard", "move_to_end", "insert")

    # ---- trace-purity (whole-program) ------------------------------------
    #: callables whose function argument executes at TRACE time, matched
    #: by last name (``seam_jit(fn)``, ``jax.vmap(fn)``, ``@jax.jit``)
    trace_stagers: tuple = ("jit", "vmap", "pmap", "seam_jit",
                            "shard_map", "shard_map_compat")
    #: …and by dotted suffix, for names too generic to match bare
    #: (``lax.map`` must not swallow the builtin ``map``)
    trace_stagers_dotted: tuple = ("lax.scan", "lax.map", "lax.cond",
                                   "lax.while_loop", "lax.fori_loop",
                                   "lax.switch", "jax.checkpoint",
                                   "jax.remat")
    #: fnmatch patterns over a callee's dotted name: calling one of
    #: these from trace-reachable code is a side effect (counter bumps,
    #: logging, IO — they run at TRACE time, once per compile, not per
    #: request; under concurrency, with foreign tracers in scope)
    trace_side_effects: tuple = ("print", "open", "input", "note_*",
                                 "_bump", "logging.*", "*.warning",
                                 "*.info", "*.debug", "*.error")

    # ---- counter-discipline (whole-program) ------------------------------
    #: modules whose counter stores the rule polices
    counter_modules: tuple = ("*/search/jit_exec.py",
                              "*/parallel/mesh_engine.py",
                              "*/search/percolator.py")
    #: the registry module (parsed for the declared key sets)
    counter_registry_modules: tuple = ("*/search/lanes.py",)
    #: names of the registry dicts inside the registry module
    counter_registry_names: tuple = ("JIT_COUNTERS",
                                     "DATA_LAYER_COUNTERS",
                                     "PERCOLATE_COUNTERS")
    #: last name of a counter-store dict (``_stats[...] += n`` /
    #: ``self.stats[...] += n``) inside a counter module
    counter_stores: tuple = ("_stats", "_data_layer", "stats")
    #: functions whose first argument is a counter key
    counter_bump_fns: tuple = ("_bump",)
    #: the OpenMetrics exporter module(s): every registry dict must be
    #: REFERENCED there (the exposition iterates the registries, so a
    #: referenced registry exports every key by construction — and an
    #: unreferenced one is a whole counter family invisible to scrapes)
    exporter_modules: tuple = ("*/observability/openmetrics.py",)

    # ---- program-cost-discipline -----------------------------------------
    #: modules whose program compiles must flow through the
    #: observed_compile seam (the compiled-program homes)
    cost_seam_modules: tuple = ("*/search/jit_exec.py",
                                "*/parallel/mesh_engine.py")
    #: the seam functions allowed to call ``.compile()`` on a lowered
    #: program (everything else routes through them)
    cost_seam_fns: tuple = ("observed_compile",)
    #: callables whose ``lane`` argument must be a PROGRAM_LANES string
    #: literal at the call site (forwarded parameters inside these
    #: functions themselves are exempt, the seam-wrapper discipline)
    cost_lane_callers: tuple = ("observed_compile", "_get_compiled")
    #: the registered program lanes (mirrors lanes.PROGRAM_LANES; the
    #: tier-1 fixture suite asserts the two stay in sync)
    program_lanes: tuple = ("segment", "segment-batch", "reader-batch",
                            "streamed", "percolate", "impact-eager",
                            "impact-pruned", "impact-rescore", "knn",
                            "mesh", "impact-mesh", "knn-mesh")
    #: gauge registries in the lane-registry module: emitted into
    #: lane_graph.json next to the counter registries and required (by
    #: counter-unexported) to be referenced by the exporter, but their
    #: keys are computed gauges — never bumped, so the unbumped check
    #: skips them
    gauge_registry_names: tuple = ("PROGRAM_COST",)

    # ---- unbounded-wait --------------------------------------------------
    #: modules where every blocking ``.result()``/``.join()``/``.get()``/
    #: ``.wait()`` must carry a timeout: the device executor, the
    #: dispatcher, the admission batcher, and the coordinator fan-out —
    #: the layers a wedged device dispatch would otherwise hang.
    #: Worker-loop homes (threadpool, cluster service) stay out: a
    #: worker idling for its next task may block without bound.
    wait_modules: tuple = ("*/search/jit_exec.py",
                           "*/search/scheduler.py",
                           "*/search/batching.py",
                           "*/search/watchdog.py",
                           "*/action/search_action.py")

    # ---- fallback-taxonomy (whole-program) -------------------------------
    #: reason-noting callables, by last name → lane whose vocabulary
    #: the literal reason must come from
    fallback_noters: tuple = (("note_plane_fallback", "plane"),
                              ("_note_plane_fallback", "plane"),
                              ("note_fallback", "plane"),
                              ("note_impact_fallback", "impact"),
                              ("note_knn_fallback", "knn"),
                              ("note_percolate_fallback", "percolate"),
                              ("note_scheduler_shed", "scheduler"),
                              ("note_planner_fallback", "planner"))
    #: the lane-registry module and its vocabulary / edge / admission
    #: dict names (the --emit-lane-graph source of truth)
    lane_registry_modules: tuple = ("*/search/lanes.py",)
    lane_reasons_name: str = "LANE_REASONS"
    lane_edges_name: str = "DECLINE_EDGES"
    lane_admissions_name: str = "LANE_ADMISSIONS"

    # ---- plan-node-spans (whole-program) ---------------------------------
    #: the planner module(s): every plan-node constructor call there
    #: must pass a literal ``plan.*`` span and a registered planner
    #: fallback reason
    planner_modules: tuple = ("*/search/planner.py",)
    #: plan-node constructor names the rule scans for
    plan_node_ctors: tuple = ("PlanNode",)
    #: required prefix of a plan node's span literal
    plan_span_prefix: str = "plan."
    #: the lane whose vocabulary plan-node ``fallback=`` literals must
    #: come from
    plan_reason_lane: str = "planner"


DEFAULT_CONFIG = LintConfig()


def module_matches(relpath: str, patterns: tuple) -> bool:
    rel = relpath.replace("\\", "/")
    return any(fnmatch.fnmatch(rel, pat) or fnmatch.fnmatch("*/" + rel, pat)
               for pat in patterns)


@dataclass
class FunctionInfo:
    node: object                       # FunctionDef | AsyncFunctionDef | Lambda
    name: str
    qualname: str
    parent: "FunctionInfo | None"
    class_name: str | None


@dataclass
class ModuleContext:
    relpath: str
    source: str
    tree: ast.Module = None
    suppressions: dict = field(default_factory=dict)   # line → [(rule, reason)]
    #: (comment line, rule) pairs a finding actually consumed — the
    #: complement is the stale-suppression audit's input
    used_suppressions: set = field(default_factory=set)
    functions: list = field(default_factory=list)
    _fn_of_node: dict = field(default_factory=dict)    # id(node) → FunctionInfo
    import_aliases: dict = field(default_factory=dict)  # alias → module path

    def __post_init__(self):
        self.tree = ast.parse(self.source)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._pl_parent = node
        self._index_suppressions()
        self._index_functions()
        self._index_imports()

    # ---- suppressions -----------------------------------------------------

    def _index_suppressions(self) -> None:
        # tokenize so only REAL comments count — a docstring describing
        # the allow syntax must not suppress anything
        import io
        import tokenize
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    self.suppressions.setdefault(
                        tok.start[0], []).append((m.group(1), m.group(2)))
        except tokenize.TokenError:
            pass

    def suppression_for(self, rule: str, node) -> "tuple | None":
        """→ (reason,) if an allow[rule] comment covers `node` (any line
        of its statement, or the line directly above)."""
        stmt = self.enclosing_stmt(node)
        lo = getattr(stmt, "lineno", node.lineno)
        hi = getattr(stmt, "end_lineno", lo)
        for line in range(lo - 1, hi + 1):
            for rid, reason in self.suppressions.get(line, ()):
                if rid == rule:
                    self.used_suppressions.add((line, rule))
                    return (reason,)
        return None

    def meta_findings(self) -> list:
        """A bare allow with no reason never suppresses — report it."""
        out = []
        for line, entries in sorted(self.suppressions.items()):
            for rid, reason in entries:
                if not reason:
                    out.append(Finding(
                        "allow-missing-reason", self.relpath, line,
                        f"suppression allow[{rid}] carries no reason "
                        f"string — every allow must say why"))
                elif rid not in RULE_FAMILIES:
                    out.append(Finding(
                        "allow-missing-reason", self.relpath, line,
                        f"suppression names unknown rule id [{rid}]"))
        return out

    def stale_findings(self, strict: bool = False) -> list:
        """The stale-suppression audit: a reasoned ``allow[rule]`` whose
        rule no longer fires on its statement suppresses nothing — it is
        dead weight that silently blesses FUTURE violations on that
        line. Warning tier by default; ``--strict-suppressions``
        promotes to a gate-failing finding. Runs AFTER every rule has
        consumed its suppressions."""
        out = []
        for line, entries in sorted(self.suppressions.items()):
            for rid, reason in entries:
                if not reason or rid not in RULE_FAMILIES:
                    continue              # allow-missing-reason's problem
                if (line, rid) not in self.used_suppressions:
                    out.append(Finding(
                        "allow-stale", self.relpath, line,
                        f"suppression allow[{rid}] no longer matches a "
                        f"finding on this statement — drop it (or fix "
                        f"the drift that moved the finding)",
                        warning=not strict))
        return out

    # ---- structure --------------------------------------------------------

    def _index_functions(self) -> None:
        def visit(node, parent_fn, class_name, prefix):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    info = FunctionInfo(child, child.name, qual,
                                        parent_fn, class_name)
                    self.functions.append(info)
                    self._fn_of_node[id(child)] = info
                    visit(child, info, class_name, qual + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, parent_fn, child.name,
                          prefix + child.name + ".")
                else:
                    visit(child, parent_fn, class_name, prefix)
        visit(self.tree, None, None, "")

    def _index_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.import_aliases[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.import_aliases[alias.asname or
                                        alias.name.split(".")[0]] = \
                        alias.name

    def parent(self, node):
        return getattr(node, "_pl_parent", None)

    def ancestors(self, node):
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_stmt(self, node):
        cur = node
        while cur is not None and not isinstance(cur, ast.stmt):
            cur = self.parent(cur)
        return cur or node

    def enclosing_function(self, node) -> "FunctionInfo | None":
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return self._fn_of_node[id(anc)]
        return None

    def function_info(self, fn_node) -> "FunctionInfo | None":
        return self._fn_of_node.get(id(fn_node))

    def enclosing_chain(self, node):
        info = self.enclosing_function(node)
        while info is not None:
            yield info
            info = info.parent


def callee_dotted(call: ast.Call) -> str:
    """Best-effort dotted name of a call's callee ('' when dynamic)."""
    return dotted(call.func)


def dotted(node) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def last_name(node) -> str:
    d = dotted(node)
    return d.rsplit(".", 1)[-1] if d else ""


def apply_suppressions(ctx: ModuleContext, findings: list, nodes: list
                       ) -> list:
    """Pair rule findings with their AST nodes and mark the suppressed
    ones (reason recorded)."""
    for f, node in zip(findings, nodes):
        hit = ctx.suppression_for(f.rule, node)
        if hit is not None and hit[0]:
            f.suppressed = True
            f.suppress_reason = hit[0]
    return findings
