"""breaker-discipline: every charge has a release reachable on all exits.

Charge sites are calls to ``<breaker>.add_estimate(...)`` and
constructions of :class:`OneShotCharge` (under any import alias). A site
passes when the reservation provably has a release path:

* the charge's enclosing class itself defines ``release`` (the pairing
  primitive — OneShotCharge.charge lives next to its release);
* the charge sits inside a ``try`` whose ``finally`` (or an ``except``
  handler) calls or registers ``.release`` — the straight-line pairing —
  or calls a function that TRANSITIVELY releases (the v2
  interprocedural extension: ``finally: self._teardown()`` where
  ``_teardown`` walks a cleanup helper in another module that releases
  counts, via the whole-program call graph);
* the same receiver has a ``.release`` call elsewhere in the function
  (the charge-before-try and delta-accounting shapes: ES charges OUTSIDE
  the try so a failed reservation is never double-released);
* the charge object ESCAPES the function — stored to an attribute /
  subscript / collection, returned, or handed to another callable
  (close/swap listeners, cache entries that release on eviction).

``breaker-double-release``: two unconditional ``x.release()`` calls on
one receiver in the same straight-line suite — the double-return shape
that under-accounts a shared breaker.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, dotted, last_name)


def _charge_aliases(ctx, cfg) -> set:
    out = set(cfg.charge_classes)
    for alias, target in ctx.import_aliases.items():
        if target.rsplit(".", 1)[-1] in cfg.charge_classes:
            out.add(alias)
    return out


def _is_charge_call(node: ast.Call, aliases: set) -> str | None:
    name = last_name(node.func)
    if name == "add_estimate" and isinstance(node.func, ast.Attribute):
        return "add_estimate"
    if name in aliases and isinstance(node.func, ast.Name):
        return "OneShotCharge"
    return None


def _releasing_fqns(program) -> set:
    """Functions that (transitively) contain a ``.release`` reference —
    computed once per program, cached on it."""
    cached = getattr(program, "_breaker_releasing", None)
    if cached is not None:
        return cached
    direct = set()
    for fqn, (ctx, info) in program.functions.items():
        for n in ast.walk(info.node):
            if isinstance(n, ast.Attribute) and n.attr == "release":
                direct.add(fqn)
                break
    out = program.transitive_marked(direct)
    program._breaker_releasing = out
    return out


def _release_in(suites, ctx=None, program=None) -> bool:
    releasing = _releasing_fqns(program) if program is not None else set()
    for sub in suites:
        for n in ast.walk(sub):
            if isinstance(n, ast.Attribute) and n.attr == "release":
                return True
            if program is not None and isinstance(n, ast.Call):
                caller = ctx.enclosing_function(n)
                if program.resolve_callable(ctx, n.func, caller) & \
                        releasing:
                    return True           # cleanup helper releases for us
    return False


def _class_defines_release(ctx, fn) -> bool:
    """Is the charge inside a class that defines release() itself (the
    pairing primitive)?"""
    if fn is None or fn.class_name is None:
        return False
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ClassDef) and node.name == fn.class_name:
            return any(isinstance(m, ast.FunctionDef) and
                       m.name == "release" for m in node.body)
    return False


def _in_guarded_try(ctx, call, fn_node, program=None) -> bool:
    for anc in ctx.ancestors(call):
        if anc is fn_node:
            break
        if isinstance(anc, ast.Try):
            if _release_in(anc.finalbody, ctx, program) or \
                    _release_in(anc.handlers, ctx, program):
                return True
    return False


def _receiver_released_in_fn(call, fn_node) -> bool:
    """`recv.add_estimate(...)` paired by any `recv.release(...)` in the
    same function (covers charge-before-try/finally and branch deltas)."""
    if not isinstance(call.func, ast.Attribute):
        return False
    recv = dotted(call.func.value)
    if not recv:
        return False
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Attribute) and n.attr == "release" and \
                dotted(n.value) == recv:
            return True
    return False


def _releasing_call_in_fn(ctx, program, fn_node) -> bool:
    """v2 interprocedural pairing: the function calls something that
    TRANSITIVELY releases (the charge-before-try + ``finally:
    cleanup_helper()`` idiom, with the helper in any module)."""
    if program is None:
        return False
    releasing = _releasing_fqns(program)
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Call):
            caller = ctx.enclosing_function(n)
            if program.resolve_callable(ctx, n.func, caller) & releasing:
                return True
    return False


def _in_receiver_chain(node, call: ast.Call) -> bool:
    """Is `node` inside `call`'s callee expression (a chained method ON
    the charge rather than the charge escaping into an argument)?"""
    return any(sub is node for sub in ast.walk(call.func))


def _escapes(ctx, call: ast.Call, fn_node) -> bool:
    """Does the charge value leave the function (stored / returned /
    registered), or get released through its bound name?"""
    cur = call
    for anc in ctx.ancestors(call):
        if anc is fn_node:
            break
        if isinstance(anc, ast.Return):
            return True
        if isinstance(anc, ast.Call) and not _in_receiver_chain(cur, anc):
            return True                 # handed to another callable
        if isinstance(anc, (ast.Assign, ast.AnnAssign)):
            targets = anc.targets if isinstance(anc, ast.Assign) \
                else [anc.target]
            if any(isinstance(t, (ast.Attribute, ast.Subscript,
                                  ast.Tuple, ast.List)) for t in targets):
                return True
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if names:
                return _name_escapes(names, fn_node, call)
        cur = anc
    return False


def _name_escapes(names: list, fn_node, origin) -> bool:
    for n in ast.walk(fn_node):
        if n is origin:
            continue
        if isinstance(n, ast.Attribute) and \
                isinstance(n.value, ast.Name) and n.value.id in names:
            if n.attr == "release":
                return True             # released (or registered) by name
            continue
        if isinstance(n, ast.Call):
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in names:
                        return True
        elif isinstance(n, ast.Assign):
            if any(isinstance(t, (ast.Attribute, ast.Subscript))
                   for t in n.targets) and \
                    any(isinstance(s, ast.Name) and s.id in names
                        for s in ast.walk(n.value)):
                return True
        elif isinstance(n, ast.Return) and n.value is not None:
            if any(isinstance(s, ast.Name) and s.id in names
                   for s in ast.walk(n.value)):
                return True
    return False


def check(ctx, cfg, program=None) -> list:
    aliases = _charge_aliases(ctx, cfg)
    findings, nodes = [], []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        kind = _is_charge_call(node, aliases)
        if kind is None:
            continue
        fn = ctx.enclosing_function(node)
        if fn is None:
            continue                    # module scope: test scaffolding
        if _class_defines_release(ctx, fn) or \
                _in_guarded_try(ctx, node, fn.node, program) or \
                _receiver_released_in_fn(node, fn.node) or \
                _releasing_call_in_fn(ctx, program, fn.node) or \
                _escapes(ctx, node, fn.node):
            continue
        findings.append(Finding(
            "breaker-unreleased", ctx.relpath, node.lineno,
            f"{kind} in {fn.qualname}() has no release pairing "
            f"reachable on all exits (no try/finally release, no "
            f"same-function release, and the charge never escapes to "
            f"a listener/cache/owner)"))
        nodes.append(node)

    # double-release: two unconditional x.release() in one suite
    for fn in ctx.functions:
        for body in _suites(fn.node):
            seen: dict = {}
            for stmt in body:
                if not isinstance(stmt, ast.Expr) or \
                        not isinstance(stmt.value, ast.Call):
                    continue
                call = stmt.value
                if last_name(call.func) != "release" or \
                        not isinstance(call.func, ast.Attribute):
                    continue
                recv = dotted(call.func.value)
                if not recv:
                    continue
                if recv in seen:
                    findings.append(Finding(
                        "breaker-double-release", ctx.relpath,
                        call.lineno,
                        f"{recv}.release() called twice in the same "
                        f"suite of {fn.qualname}() (first at line "
                        f"{seen[recv]}) — double-releasing "
                        f"under-accounts the breaker"))
                    nodes.append(call)
                else:
                    seen[recv] = call.lineno
    return apply_suppressions(ctx, findings, nodes)


def _suites(fn_node):
    """Every statement suite of a function, NOT descending into nested
    defs (their suites are visited when their own FunctionInfo is)."""
    stack = [fn_node]
    while stack:
        n = stack.pop()
        for attr in ("body", "orelse", "finalbody"):
            body = getattr(n, attr, None)
            if isinstance(body, list) and body:
                if n is not fn_node and isinstance(
                        n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                yield body
                stack.extend(s for s in body if not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef)))
        for h in getattr(n, "handlers", ()) or ():
            if h.body:
                yield h.body
                stack.extend(h.body)
