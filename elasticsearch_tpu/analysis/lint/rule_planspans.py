"""plan-node-spans: every planner node is observable and taxonomized.

The cost-driven planner (``search/planner.py``) composes lane-served
sub-plan nodes into one compiled dispatch; the only evidence a node
ever existed is its span (profiled responses, the
predicted-vs-measured cost ledger) and its fallback reason (the lane
graph). Two rules keep both closed:

* ``plan-node-unspanned`` — every ``PlanNode(...)`` construction in a
  planner module must pass a literal ``span=`` beginning with the
  ``plan.`` prefix. An unspanned node launches a real device program
  that never appears in profiled responses — the fused dispatch
  becomes invisible to the cost observatory;
* ``plan-node-unregistered-reason`` — every node's ``fallback=`` must
  be a string literal from the registered planner-lane vocabulary
  (``lanes.LANE_REASONS["planner"]``). An unregistered reason forks
  the fallback taxonomy exactly like a typo'd ``note_*_fallback``
  reason would — dashboards and the lane-graph artifact disagree.
  Skipped when the lane registry is not part of the linted set
  (single-file fixture runs), like fallback-unused-reason.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, last_name, module_matches)
from elasticsearch_tpu.analysis.lint.rule_fallback import lane_registry

#: ctor signature when arguments are passed positionally:
#: ``PlanNode(lane, span, fallback, ...)``
_SPAN_ARG, _FALLBACK_ARG = 1, 2


def _arg(call: ast.Call, kwname: str, idx: int):
    for kw in call.keywords:
        if kw.arg == kwname:
            return kw.value
    if len(call.args) > idx:
        return call.args[idx]
    return None


def check_program(program, cfg) -> list:
    hit = lane_registry(program, cfg)
    vocab = hit[0].get(cfg.plan_reason_lane) if hit is not None else None

    out: list = []
    for ctx in program.contexts:
        if not module_matches(ctx.relpath, cfg.planner_modules):
            continue
        findings, nodes = [], []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or \
                    last_name(node.func) not in cfg.plan_node_ctors:
                continue
            span = _arg(node, "span", _SPAN_ARG)
            if not (isinstance(span, ast.Constant)
                    and isinstance(span.value, str)
                    and span.value.startswith(cfg.plan_span_prefix)):
                findings.append(Finding(
                    "plan-node-unspanned", ctx.relpath, node.lineno,
                    f"plan node is constructed without a literal span= "
                    f"starting with [{cfg.plan_span_prefix}] — an "
                    f"unspanned node never reaches profiled responses "
                    f"or the predicted-vs-measured cost ledger"))
                nodes.append(node)
            if vocab is None:
                continue              # registry not in the linted set
            fb = _arg(node, "fallback", _FALLBACK_ARG)
            if not (isinstance(fb, ast.Constant)
                    and isinstance(fb.value, str) and fb.value in vocab):
                shown = fb.value if isinstance(fb, ast.Constant) \
                    else "<dynamic>"
                findings.append(Finding(
                    "plan-node-unregistered-reason", ctx.relpath,
                    node.lineno,
                    f"plan-node fallback [{shown}] is not a literal "
                    f"from the registered "
                    f"[{cfg.plan_reason_lane}]-lane vocabulary — add "
                    f"it to lanes.LANE_REASONS"
                    f"[{cfg.plan_reason_lane!r}] (the taxonomy is "
                    f"closed)"))
                nodes.append(node)
        out.extend(apply_suppressions(ctx, findings, nodes))
    return out
