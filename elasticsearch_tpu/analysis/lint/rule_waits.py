"""unbounded-wait: every blocking wait on the serving path is bounded.

``unbounded-wait``: a zero-argument ``.result()`` / ``.join()`` /
``.get()`` / ``.wait()`` call — no positional timeout, no ``timeout=``
keyword — inside a wait-policed module (``cfg.wait_modules``: the
dispatcher, the device executor, the admission batcher, the
coordinator). An accelerator dispatch or transfer that wedges cannot be
cancelled from Python; the ONLY stall-tolerance mechanism the serving
path has is that every wait on such work carries a deadline and fails
over when it fires (watchdog envelope, request deadline, stall
ceiling). One unbounded ``fut.result()`` reintroduces the hung-request
mode the whole ladder exists to prevent — the wait parks a pool thread
forever and the caller's caller inherits the hang.

The attribute-name match is deliberately coarse (any ``.get()`` with
zero arguments, not just ``queue.Queue.get``): in these modules a
bare blocking accessor is wrong regardless of receiver type, and
bounded calls — ``fut.result(wait_s)``, ``q.get(timeout=0.25)``,
``t.join(5.0)`` — never match. Intentional forever-waits (a worker
loop idling for its next task) live outside ``wait_modules`` or carry
an ``# estpu: allow[unbounded-wait] <reason>`` with the argument for
why that thread may legitimately block without bound.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, module_matches)

#: blocking-call attribute names the rule polices when called with no
#: timeout: Future.result / Thread.join / Queue.get / Event.wait
WAIT_ATTRS = ("result", "join", "get", "wait")


def _is_unbounded_wait(node: ast.Call) -> str | None:
    """→ the wait attr name when `node` is a zero-timeout blocking call."""
    if not isinstance(node.func, ast.Attribute):
        return None
    attr = node.func.attr
    if attr not in WAIT_ATTRS:
        return None
    if node.args:
        return None                    # positional timeout (or a key/arg)
    if any(kw.arg == "timeout" for kw in node.keywords):
        return None
    if any(kw.arg is None for kw in node.keywords):
        return None                    # **kwargs may carry a timeout
    return attr


def check(ctx, cfg, program=None) -> list:
    if not module_matches(ctx.relpath, cfg.wait_modules):
        return []
    findings, nodes = [], []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        attr = _is_unbounded_wait(node)
        if attr is None:
            continue
        fn = ctx.enclosing_function(node)
        where = f" in {fn.qualname}()" if fn is not None else ""
        findings.append(Finding(
            "unbounded-wait", ctx.relpath, node.lineno,
            f".{attr}() with no timeout{where} — a wedged device "
            f"dispatch cannot be cancelled, so every serving-path wait "
            f"must carry a deadline and fail over when it fires; pass "
            f"a timeout (remaining deadline, watchdog envelope, or "
            f"stall ceiling) and handle the expiry"))
        nodes.append(node)
    return apply_suppressions(ctx, findings, nodes)
