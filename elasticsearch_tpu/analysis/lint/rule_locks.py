"""lock-discipline: the acquisition graph and guarded-state writes.

Lock identities resolve statically:

* ``with self._lock:`` inside class C of module m → ``m.C._lock``;
* ``with _cache_lock:`` on a module global → ``m._cache_lock``;
* ``with singleton._lock:`` where ``singleton = ClassName(...)`` at
  module level → ``m.ClassName._lock``.

``lock-order``: edges A→B are collected from (a) a ``with B`` lexically
nested under ``with A`` and (b) one-level interprocedural resolution —
while holding A, a call to a module-local function / same-class method /
imported-module function whose body directly acquires B. A pair with
edges both ways is a potential deadlock; both acquisition sites are
named. Locks constructed as ``RLock()`` are reentrant, so A→A self
edges are reported only for plain ``Lock()``.

``lock-unguarded-state``: a module-level mutable container (or an
instance attribute bound to one in ``__init__``) that is mutated under a
lock ANYWHERE is lock-owned; every other mutation of it must hold the
same lock. Exemptions: ``__init__`` (construction), methods named
``*_locked`` (the caller-holds-the-lock convention), module scope.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, dotted, last_name)

_MUTABLE_CTORS = ("dict", "list", "set", "OrderedDict", "defaultdict",
                  "deque")


def _modkey(relpath: str) -> str:
    return relpath.replace("\\", "/").rsplit(".py", 1)[0] \
        .replace("/", ".")


@dataclass
class LockSite:
    lock: str          # resolved identity
    relpath: str
    line: int


@dataclass
class ModuleLockInfo:
    modkey: str
    relpath: str
    #: lock identity → [LockSite] (every acquisition)
    acquisitions: dict = field(default_factory=dict)
    #: fn qualname → [lock identities it DIRECTLY acquires]
    fn_locks: dict = field(default_factory=dict)
    #: edges: (outer, inner) → (site_outer, site_inner)
    edges: dict = field(default_factory=dict)
    #: calls made while holding a lock: (lock, callee_repr, LockSite)
    held_calls: list = field(default_factory=list)
    #: lock identity → is reentrant (RLock)
    reentrant: dict = field(default_factory=dict)
    #: import alias → module dotted path
    import_aliases: dict = field(default_factory=dict)
    #: module-level singleton name → class name
    singletons: dict = field(default_factory=dict)
    #: every name bound at module scope (lock-identity resolution)
    module_names: set = field(default_factory=set)


def _lockish(expr) -> bool:
    name = last_name(expr)
    return bool(name) and "lock" in name.lower()


def _resolve_lock(ctx, info, expr, class_name) -> str | None:
    """Static identity of a lock expression, or None when dynamic."""
    if isinstance(expr, ast.Name):
        if expr.id in info.module_names:
            return f"{info.modkey}.{expr.id}"
        fn = ctx.enclosing_function(expr)
        scope = fn.qualname if fn is not None else "<module>"
        return f"{info.modkey}.{scope}.{expr.id}"   # function-local lock
    if isinstance(expr, ast.Attribute):
        base = expr.value
        if isinstance(base, ast.Name):
            if base.id == "self" and class_name:
                return f"{info.modkey}.{class_name}.{expr.attr}"
            cls = info.singletons.get(base.id)
            if cls is not None:
                return f"{info.modkey}.{cls}.{expr.attr}"
            mod = info.import_aliases.get(base.id)
            if mod is not None:
                return f"{mod}.{expr.attr}"
    return None


def collect(ctx, cfg) -> ModuleLockInfo:
    info = ModuleLockInfo(_modkey(ctx.relpath), ctx.relpath)
    info.import_aliases = dict(ctx.import_aliases)
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign):
            info.module_names.update(
                t.id for t in node.targets if isinstance(t, ast.Name))
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            info.module_names.add(node.target.id)
    # module-level singletons + lock reentrancy
    for node in ctx.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            ctor = last_name(node.value.func)
            for t in node.targets:
                if not isinstance(t, ast.Name):
                    continue
                if ctor and ctor[0].isupper() and ctor not in (
                        "OrderedDict", "RLock", "Lock"):
                    info.singletons[t.id] = ctor
                if ctor in ("Lock", "RLock"):
                    info.reentrant[f"{info.modkey}.{t.id}"] = \
                        ctor == "RLock"
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call) and \
                last_name(node.value.func) in ("Lock", "RLock"):
            for t in node.targets:
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and \
                        t.value.id == "self":
                    fn = ctx.enclosing_function(node)
                    if fn is not None and fn.class_name:
                        ident = f"{info.modkey}.{fn.class_name}.{t.attr}"
                        info.reentrant[ident] = \
                            last_name(node.value.func) == "RLock"

    # acquisitions, lexical nesting, held calls
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.With):
            continue
        fn = ctx.enclosing_function(node)
        class_name = fn.class_name if fn else None
        for item in node.items:
            expr = item.context_expr
            if not _lockish(expr):
                continue
            ident = _resolve_lock(ctx, info, expr, class_name)
            if ident is None:
                continue
            site = LockSite(ident, ctx.relpath, node.lineno)
            info.acquisitions.setdefault(ident, []).append(site)
            if fn is not None:
                info.fn_locks.setdefault(fn.qualname, []).append(ident)
            # lexical nesting under an outer lock
            for anc in ctx.ancestors(node):
                if isinstance(anc, ast.With):
                    for o_item in anc.items:
                        o_expr = o_item.context_expr
                        if not _lockish(o_expr):
                            continue
                        o_fn = ctx.enclosing_function(anc)
                        o_ident = _resolve_lock(
                            ctx, info, o_expr,
                            o_fn.class_name if o_fn else None)
                        if o_ident is not None:
                            o_site = LockSite(o_ident, ctx.relpath,
                                              anc.lineno)
                            info.edges.setdefault(
                                (o_ident, ident), (o_site, site))
            # calls made inside this with body
            for n in ast.walk(node):
                if isinstance(n, ast.Call):
                    callee = _callee_repr(ctx, info, n, class_name)
                    if callee is not None:
                        info.held_calls.append(
                            (ident, callee,
                             LockSite(ident, ctx.relpath, n.lineno)))
    return info


def _callee_repr(ctx, info, call, class_name) -> "tuple | None":
    """→ ('local', name) | ('method', class, name) | ('module', modpath,
    name) for resolvable callees."""
    f = call.func
    if isinstance(f, ast.Name):
        return ("local", f.id)
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        base = f.value.id
        if base == "self" and class_name:
            return ("method", class_name, f.attr)
        cls = info.singletons.get(base)
        if cls is not None:
            return ("method", cls, f.attr)
        mod = info.import_aliases.get(base)
        if mod is not None:
            return ("module", mod, f.attr)
    return None


def lock_graph(infos: list, cfg=None, program=None) -> dict:
    """(outer, inner) → (LockSite outer, LockSite inner) over the whole
    tree: every lexically-nested acquisition plus interprocedural
    resolution of calls made while holding a lock — one level through
    the module-local tables (module-local functions, same-class /
    singleton methods, imported-module functions), and, when the
    whole-program index is available, the TRANSITIVE closure of the
    callee's call-graph reach (v2: while holding A, a helper three
    modules away that acquires B still contributes the A→B edge).
    finalize() reports on this graph; the runtime watchdog
    (elasticsearch_tpu.analysis.watchdog) asserts it."""
    local_fns: dict = {}      # (modkey, name) → [lock identities]
    method_fns: dict = {}     # (class, name) → [[lock identities]]
    locks_by_fqn: dict = {}   # program fqn → [lock identities]
    for info in infos:
        for qual, locks in info.fn_locks.items():
            parts = qual.split(".")
            name = parts[-1]
            local_fns.setdefault((info.modkey, name), []).extend(locks)
            if len(parts) >= 2:
                method_fns.setdefault((parts[-2], name), []).append(locks)
            locks_by_fqn[f"{info.modkey}.{qual}"] = list(locks)
    modkey_of = {info.modkey.rsplit(".", 1)[-1]: info.modkey
                 for info in infos}

    def closure_locks(fqns) -> list:
        if program is None:
            return []
        out = []
        for fqn in program.reachable_from(set(fqns)):
            out.extend(locks_by_fqn.get(fqn, ()))
        return out

    edges: dict = {}
    for info in infos:
        edges.update(info.edges)
        for held, callee, site in info.held_calls:
            targets = []
            fqn_seeds = []
            if callee[0] == "local":
                targets = local_fns.get((info.modkey, callee[1]), [])
                fqn_seeds = [f"{info.modkey}.{callee[1]}"]
            elif callee[0] == "method":
                for locks in method_fns.get((callee[1], callee[2]), ()):
                    targets.extend(locks)
                if program is not None:
                    fqn_seeds = list(program.methods.get(
                        (callee[1], callee[2]), ()))
            elif callee[0] == "module":
                mod = callee[1]
                key = modkey_of.get(mod.rsplit(".", 1)[-1])
                if key is not None:
                    targets = local_fns.get((key, callee[2]), [])
                    fqn_seeds = [f"{key}.{callee[2]}"]
            targets = list(targets) + closure_locks(fqn_seeds)
            for inner in targets:
                edges.setdefault((held, inner),
                                 (site, LockSite(inner, site.relpath,
                                                 site.line)))
    return edges


def finalize(infos: list, cfg, program=None) -> list:
    """Cross-module pass: resolve held calls into edges, then report
    inconsistent lock-order pairs (and non-reentrant self cycles)."""
    edges = lock_graph(infos, cfg, program)

    reentrant: dict = {}
    for info in infos:
        reentrant.update(info.reentrant)

    findings, nodes = [], []
    reported = set()
    for (a, b), (site_a, site_b) in sorted(
            edges.items(), key=lambda kv: (kv[1][0].relpath,
                                           kv[1][0].line)):
        if a == b:
            if not reentrant.get(a, True):
                key = (a, a)
                if key not in reported:
                    reported.add(key)
                    findings.append(Finding(
                        "lock-order", site_a.relpath, site_a.line,
                        f"non-reentrant lock {a} re-acquired while "
                        f"held (self-deadlock)"))
            continue
        if (b, a) in edges and (b, a) not in reported:
            reported.add((a, b))
            other = edges[(b, a)][0]
            findings.append(Finding(
                "lock-order", site_a.relpath, site_a.line,
                f"inconsistent lock order: {a} → {b} here, but "
                f"{b} → {a} at {other.relpath}:{other.line} — "
                f"potential deadlock"))
    return findings


def lock_ranks(edges: dict) -> dict:
    """Deterministic topological ranks over the acquisition DAG (cycle
    back-edges — already reported by lock-order — are dropped)."""
    nodes = sorted({n for e in edges for n in e})
    out_edges: dict = {n: set() for n in nodes}
    for (a, b) in edges:
        if a != b and (b, a) not in edges:
            out_edges[a].add(b)
    ranks: dict = {}

    def depth(n, seen):
        if n in ranks:
            return ranks[n]
        if n in seen:
            return 0
        seen.add(n)
        d = 0
        for m in sorted(out_edges[n]):
            d = max(d, depth(m, seen) + 1)
        ranks[n] = d
        return d
    for n in nodes:
        depth(n, set())
    # outer locks (acquired first) get LOWER rank numbers
    mx = max(ranks.values(), default=0)
    return {n: mx - d for n, d in ranks.items()}


# ---------------------------------------------------------------------------
# lock-unguarded-state (per module)
# ---------------------------------------------------------------------------

def check_state(ctx, cfg, program=None) -> list:
    info = collect(ctx, cfg)
    candidates = _state_candidates(ctx)
    if not candidates:
        return []
    mutations: dict = {}    # state ident → [(lock|None, node, fn)]
    for node in ast.walk(ctx.tree):
        target = _mutation_target(ctx, node, cfg)
        if target is None:
            continue
        ident = _state_ident(ctx, info, target)
        if ident is None or ident not in candidates:
            continue
        fn = ctx.enclosing_function(node)
        lock = _held_lock(ctx, info, node)
        mutations.setdefault(ident, []).append((lock, node, fn))

    call_sites = _call_sites(ctx, info)
    findings, nodes = [], []
    for ident, muts in sorted(mutations.items()):
        owners = sorted({lock for lock, _, _ in muts if lock is not None})
        if not owners:
            continue                    # never locked anywhere: not owned
        owner = owners[0] if len(owners) == 1 else None
        for lock, node, fn in muts:
            if lock is not None:
                continue
            if fn is None:
                continue                # module-scope init
            if fn.name == "__init__" or \
                    fn.name.endswith(cfg.locked_suffix):
                continue
            if owner is not None and \
                    _lock_dominated(fn, owner, call_sites, set()):
                continue                # every caller holds the lock
            findings.append(Finding(
                "lock-unguarded-state", ctx.relpath, node.lineno,
                f"{ident.rsplit('.', 1)[-1]} is mutated under "
                f"{owner or ' / '.join(owners)} elsewhere but written "
                f"here in {fn.qualname}() without holding it"))
            nodes.append(node)
    return apply_suppressions(ctx, findings, nodes)


def _call_sites(ctx, info) -> dict:
    """fn qualname → [(caller FunctionInfo, held lock ident | None)] for
    every module-resolvable call."""
    by_key = {}
    for fn in ctx.functions:
        by_key.setdefault((fn.class_name, fn.name), []).append(fn)
        by_key.setdefault((None, fn.name), []).append(fn)
    sites: dict = {}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        caller = ctx.enclosing_function(node)
        if caller is None:
            continue
        f = node.func
        targets = []
        if isinstance(f, ast.Name):
            targets = [t for t in by_key.get((None, f.id), ())
                       if t.class_name is None]
        elif isinstance(f, ast.Attribute) and isinstance(f.value,
                                                         ast.Name):
            if f.value.id == "self" and caller.class_name:
                targets = by_key.get((caller.class_name, f.attr), [])
            else:
                cls = info.singletons.get(f.value.id)
                if cls is not None:
                    targets = by_key.get((cls, f.attr), [])
        held = _held_lock(ctx, info, node)
        for t in targets:
            sites.setdefault(t.qualname, []).append((caller, held))
    return sites


def _lock_dominated(fn, owner: str, call_sites: dict, visiting: set
                    ) -> bool:
    """Every module-local call site of `fn` holds `owner` — directly, by
    being construction (`__init__` of the same class), or transitively
    through another dominated caller."""
    if fn.qualname in visiting:
        return True                     # cycle: optimistic, callers decide
    entries = call_sites.get(fn.qualname)
    if not entries:
        return False
    visiting = visiting | {fn.qualname}
    for caller, held in entries:
        if held == owner:
            continue
        if caller.name == "__init__" and \
                caller.class_name == fn.class_name:
            continue
        if _lock_dominated(caller, owner, call_sites, visiting):
            continue
        return False
    return True


def _state_candidates(ctx) -> set:
    out = set()
    modkey = _modkey(ctx.relpath)
    for node in ctx.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            if _mutable_value(node.value):
                out.update(f"{modkey}.{t.id}" for t in targets
                           if isinstance(t, ast.Name))
    for fn in ctx.functions:
        if fn.name != "__init__" or fn.class_name is None:
            continue
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if _mutable_value(node.value):
                    for t in targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            out.add(f"{modkey}.{fn.class_name}.{t.attr}")
    return out


def _mutable_value(value) -> bool:
    if value is None:
        return False
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call) and \
            last_name(value.func) in _MUTABLE_CTORS:
        return True
    return False


def _mutation_target(ctx, node, cfg):
    """→ the expression naming the mutated container, or None."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                return t.value
    elif isinstance(node, ast.AugAssign):
        t = node.target
        return t.value if isinstance(t, ast.Subscript) else t
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                return t.value
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in cfg.mutators:
        return node.func.value
    return None


def _state_ident(ctx, info, target) -> str | None:
    if isinstance(target, ast.Name):
        return f"{info.modkey}.{target.id}"
    if isinstance(target, ast.Attribute) and \
            isinstance(target.value, ast.Name):
        if target.value.id == "self":
            fn = ctx.enclosing_function(target)
            if fn is not None and fn.class_name:
                return f"{info.modkey}.{fn.class_name}.{target.attr}"
        cls = info.singletons.get(target.value.id)
        if cls is not None:
            return f"{info.modkey}.{cls}.{target.attr}"
    return None


def _held_lock(ctx, info, node) -> str | None:
    fn = ctx.enclosing_function(node)
    class_name = fn.class_name if fn else None
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if _lockish(item.context_expr):
                    ident = _resolve_lock(ctx, info, item.context_expr,
                                          class_name)
                    if ident is not None:
                        return ident
    return None
