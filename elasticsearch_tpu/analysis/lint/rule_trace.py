"""trace-purity: nothing reachable from a traced body touches host state.

A function is TRACE-REACHABLE when it is staged by ``seam_jit`` /
``jax.jit`` / ``vmap`` / ``lax.scan`` / ``lax.map`` (decorated, passed
by name, wrapped in ``partial``, or called from a staged lambda), or
transitively called from one (ProgramIndex.traced). Python statements
in such a function run at TRACE time: once per compile — not per
request — and, under concurrent tracing, with FOREIGN tracers live on
the stack. The canonical bug (PR 10, distilled in
tests/lint_fixtures/trace_purity_pos.py): an ``import`` executed inside
a traced body let jax cache another request's tracers into the imported
module's globals — "compiled for N+3 inputs" under concurrency.

Rules, each anchored at the impure statement with the call path from
the staged seed:

* ``trace-impure-import`` — any ``import`` / ``from … import``
  statement (module-cache writes + arbitrary module-level execution at
  trace time);
* ``trace-impure-global`` — a ``global`` declaration (rebinding module
  state per compile);
* ``trace-impure-state-write`` — mutating a module-level container
  (``_cache[k] = v``, ``.append``, ``+=`` …), directly or through an
  imported module's attribute;
* ``trace-impure-call`` — calling a configured side-effecting function
  (``note_*`` counter bumps, ``print``/``open``, logging): the effect
  fires per compile, silently wrong under the program cache;
* ``trace-impure-capture`` — READING module-level mutable state (a
  dict/list/set that something, somewhere, mutates): the value is baked
  at trace time, so later mutations never reach the compiled program —
  and tracer objects can leak INTO it. Constant lookup tables (never
  mutated) pass.
"""

from __future__ import annotations

import ast
import fnmatch

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, dotted, last_name)
from elasticsearch_tpu.analysis.lint.program import modkey_for, short_fqn

_MUTABLE_CTORS = ("dict", "list", "set", "OrderedDict", "defaultdict",
                  "deque")


def _is_mutable_literal(value) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    return isinstance(value, ast.Call) and \
        last_name(value.func) in _MUTABLE_CTORS


def _mutation_target(node, cfg):
    """The expression naming a mutated container, or None (the
    lock-discipline detection, shared shape)."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                return t.value
    elif isinstance(node, ast.AugAssign):
        t = node.target
        return t.value if isinstance(t, ast.Subscript) else t
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                return t.value
    elif isinstance(node, ast.Call) and \
            isinstance(node.func, ast.Attribute) and \
            node.func.attr in cfg.mutators:
        return node.func.value
    return None


def _mutable_module_state(program) -> set:
    """(modkey, name) of module-level mutable containers that some
    function ANYWHERE in the program mutates — true shared state, as
    opposed to constant lookup tables."""
    declared: set = set()
    for modkey, mod in program.modules.items():
        for node in mod.ctx.tree.body:
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                if node.value is not None and \
                        _is_mutable_literal(node.value):
                    declared.update(
                        (modkey, t.id) for t in targets
                        if isinstance(t, ast.Name))
    mutated: set = set()
    for ctx in program.contexts:
        modkey = modkey_for(ctx.relpath)
        for node in ast.walk(ctx.tree):
            target = _mutation_target(node, program.cfg)
            if target is None or ctx.enclosing_function(node) is None:
                continue                  # module-scope init is declaration
            if isinstance(target, ast.Name):
                mutated.add((modkey, target.id))
            elif isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name):
                imported = ctx.import_aliases.get(target.value.id)
                if imported is not None:
                    tmod = program.resolve_module(imported)
                    if tmod is not None:
                        mutated.add((tmod.modkey, target.attr))
    return declared & mutated


def _local_names(fn_node) -> set:
    """Names bound locally in a function (params, plain assignments,
    loop/with/except targets, comprehension vars) — these shadow module
    globals for the state rules."""
    out = set()
    args = fn_node.args
    for a in (args.args + args.kwonlyargs + args.posonlyargs
              if hasattr(args, "posonlyargs")
              else args.args + args.kwonlyargs):
        out.add(a.arg)
    for extra in (args.vararg, args.kwarg):
        if extra is not None:
            out.add(extra.arg)
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name) and \
                            isinstance(sub.ctx, ast.Store):
                        out.add(sub.id)
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(n.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        elif isinstance(n, (ast.withitem,)) and n.optional_vars is not None:
            for sub in ast.walk(n.optional_vars):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        elif isinstance(n, ast.comprehension):
            for sub in ast.walk(n.target):
                if isinstance(sub, ast.Name):
                    out.add(sub.id)
        elif isinstance(n, ast.ExceptHandler) and n.name:
            out.add(n.name)
    return out


def _side_effect_match(call: ast.Call, cfg) -> str | None:
    d = dotted(call.func)
    name = last_name(call.func)
    for pat in cfg.trace_side_effects:
        if (d and fnmatch.fnmatch(d, pat)) or \
                (name and fnmatch.fnmatch(name, pat)):
            return d or name
    return None


def check_program(program, cfg) -> list:
    reached, _ = program.traced()
    mutable_state = _mutable_module_state(program)
    by_ctx: dict = {}                     # ctx → (findings, nodes)

    def report(ctx, rule, node, message):
        _, findings, nodes = by_ctx.setdefault(ctx.relpath, (ctx, [], []))
        findings.append(Finding(rule, ctx.relpath, node.lineno, message))
        nodes.append(node)

    for fqn in sorted(reached):
        entry = program.functions.get(fqn)
        if entry is None:
            continue
        ctx, info = entry
        modkey = modkey_for(ctx.relpath)
        mod = program.modules.get(modkey)
        locals_ = _local_names(info.node)
        path = program.trace_path(fqn)
        reported_state: set = set()       # (lineno, name): write > capture
        for node in ast.walk(info.node):
            if not isinstance(node, (ast.stmt, ast.expr)):
                continue                  # ctx/operator singletons share
                                          # parent links across trees
            if node is not info.node and isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue                  # nested defs report as themselves
            owner = ctx.enclosing_function(node)
            if owner is not info:
                continue
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                names = ", ".join(a.name for a in node.names)
                report(ctx, "trace-impure-import", node,
                       f"import of [{names}] inside the traced body of "
                       f"{short_fqn(fqn)}() — imports at trace time "
                       f"cache foreign tracers into module globals (the "
                       f"PR 10 'compiled for N+3 inputs' bug); import "
                       f"at module level instead (trace path: {path})")
                continue
            if isinstance(node, ast.Global):
                report(ctx, "trace-impure-global", node,
                       f"`global {', '.join(node.names)}` inside the "
                       f"traced body of {short_fqn(fqn)}() rebinds "
                       f"module state once per COMPILE, not per request "
                       f"(trace path: {path})")
                continue
            target = _mutation_target(node, cfg)
            if target is not None:
                state = None
                if isinstance(target, ast.Name) and \
                        target.id not in locals_ and mod is not None and \
                        target.id in mod.module_names:
                    state = target.id
                elif isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id in ctx.import_aliases:
                    tmod = program.resolve_module(
                        ctx.import_aliases[target.value.id])
                    if tmod is not None and \
                            target.attr in tmod.module_names:
                        state = f"{target.value.id}.{target.attr}"
                if state is not None:
                    reported_state.add((node.lineno, state.split(".")[-1]))
                    report(ctx, "trace-impure-state-write", node,
                           f"traced body of {short_fqn(fqn)}() mutates "
                           f"module state [{state}] — the write happens "
                           f"at trace time, once per compile, possibly "
                           f"holding tracer objects (trace path: "
                           f"{path})")
            if isinstance(node, ast.Call):
                hit = _side_effect_match(node, cfg)
                if hit is not None:
                    report(ctx, "trace-impure-call", node,
                           f"side-effecting call {hit}() inside the "
                           f"traced body of {short_fqn(fqn)}() fires "
                           f"once per COMPILE (program-cache hits skip "
                           f"it entirely) — hoist it to the dispatch "
                           f"site (trace path: {path})")
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id not in locals_ and \
                    (modkey, node.id) in mutable_state and \
                    (node.lineno, node.id) not in reported_state:
                report(ctx, "trace-impure-capture", node,
                       f"traced body of {short_fqn(fqn)}() captures "
                       f"mutable module state [{node.id}] — the value "
                       f"is baked at trace time, so later mutations "
                       f"never reach the compiled program; pass it as "
                       f"an argument or snapshot an immutable view "
                       f"(trace path: {path})")

    out = []
    for ctx, findings, nodes in by_ctx.values():
        out.extend(apply_suppressions(ctx, findings, nodes))
    return out
