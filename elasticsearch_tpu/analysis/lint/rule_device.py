"""device-seam coverage: every device touchpoint rides the fault seam.

``device-raw-call``: outside the allowlisted seam modules
(``search/jit_exec.py``, ``parallel/mesh_engine.py``, ``ops/*``) any raw
``jax.device_put`` / ``jax.block_until_ready`` / ``.block_until_ready()``
reference is an error, as is a ``jax.jit`` call constructed inside a
function body (module-level kernel definitions — the ``ops/*`` decorator
pattern — compile once per static shape and are allowed). Non-seam code
routes uploads/compiles through the jit_exec seam wrappers
(``seam_device_put`` / ``seam_jit``) so chaos can inject there and the
plane breaker sees the error.

``device-unguarded``: inside seam modules, every ``jax.device_put`` and
program-compile call (``jax.jit`` / ``.lower().compile()``) in a
function body must be DOMINATED by a ``device_fault_point(<site>)`` call
naming a known site class — lexically earlier in the same function, in
an enclosing function, or the call lives in a closure handed to the
``_get_compiled`` trampoline (which guards before invoking it).

``device-unknown-site``: a ``device_fault_point`` call whose site is not
a known class (or not a string literal) — the chaos scheme would never
draw it.
"""

from __future__ import annotations

import ast

from elasticsearch_tpu.analysis.lint.context import (
    Finding, apply_suppressions, dotted, last_name, module_matches)

_RAW_DEVICE = {"jax.device_put", "jax.block_until_ready"}


def _device_ref_kind(node, ctx) -> str | None:
    """Classify a raw device reference: 'device_put', 'block', 'jit'."""
    d = dotted(node)
    if d in ("jax.device_put",):
        return "device_put"
    if d == "jax.block_until_ready":
        return "block"
    if isinstance(node, ast.Attribute) and \
            node.attr == "block_until_ready":
        return "block"
    if d == "jax.jit":
        return "jit"
    return None


def _fault_sites_before(ctx, cfg, fn, lineno) -> list:
    """Site literals of device_fault_point calls in `fn` (or enclosing
    functions) at or before `lineno`."""
    sites = []
    info = fn
    while info is not None:
        for n in ast.walk(info.node):
            if isinstance(n, ast.Call) and \
                    last_name(n.func) in cfg.fault_point_names and \
                    n.lineno <= lineno and n.args:
                a = n.args[0]
                if isinstance(a, ast.Constant) and isinstance(a.value, str):
                    sites.append(a.value)
        info = info.parent
    return sites


def _wrapper_forwards_guard(cfg, fn, lineno) -> bool:
    """Inside a registered seam WRAPPER (seam_device_put / seam_jit) the
    fault point forwards the caller's site parameter —
    ``device_fault_point(site)`` with ``site`` a parameter Name. The
    literal is validated at every wrapper call site instead, so a
    forwarded guard at/above `lineno` dominates the wrapper body."""
    if fn is None or fn.name not in cfg.seam_wrappers:
        return False
    params = {a.arg for a in fn.node.args.args + fn.node.args.kwonlyargs}
    for n in ast.walk(fn.node):
        if isinstance(n, ast.Call) and \
                last_name(n.func) in cfg.fault_point_names and \
                n.lineno <= lineno and n.args and \
                isinstance(n.args[0], ast.Name) and \
                n.args[0].id in params:
            return True
    return False


def _in_trampoline_closure(ctx, cfg, fn) -> bool:
    """Is `fn` (or an enclosing def) passed BY NAME to a guarded
    trampoline like _get_compiled in its enclosing scope?"""
    info = fn
    while info is not None:
        outer = info.parent
        scope = outer.node if outer is not None else ctx.tree
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and \
                    last_name(n.func) in cfg.trampolines:
                for arg in n.args:
                    if isinstance(arg, ast.Name) and arg.id == info.name:
                        return True
        info = outer
    return False


def _effective_function(ctx, node):
    """Enclosing function, treating a DECORATOR expression as belonging
    to the scope the decorated function is defined in — a module-level
    ``@partial(jax.jit, ...)`` kernel is a once-per-shape compile, not a
    per-request construction."""
    fn = ctx.enclosing_function(node)
    if fn is not None and any(
            any(sub is node for sub in ast.walk(dec))
            for dec in fn.node.decorator_list):
        return fn.parent
    return fn


def check(ctx, cfg, program=None) -> list:
    in_seam = module_matches(ctx.relpath, cfg.seam_modules)
    findings, nodes = [], []

    for node in ast.walk(ctx.tree):
        # --- device_fault_point site vocabulary ---------------------------
        if isinstance(node, ast.Call) and \
                last_name(node.func) in cfg.fault_point_names:
            ok = (node.args and isinstance(node.args[0], ast.Constant)
                  and node.args[0].value in cfg.known_sites)
            fn0 = ctx.enclosing_function(node)
            if not ok and fn0 is not None and \
                    fn0.name in cfg.seam_wrappers and node.args and \
                    isinstance(node.args[0], ast.Name):
                ok = True               # wrapper forwards its caller's
            if not ok:                  # literal (checked at call sites)
                findings.append(Finding(
                    "device-unknown-site", ctx.relpath, node.lineno,
                    f"device_fault_point site must be a string literal "
                    f"from {sorted(cfg.known_sites)} — the chaos scheme "
                    f"never draws an unknown site"))
                nodes.append(node)
            continue
        # seam-wrapper call sites: the forwarded site literal is checked
        # here instead of inside the wrapper
        if isinstance(node, ast.Call) and \
                last_name(node.func) in cfg.seam_wrappers:
            site_arg = None
            for kw in node.keywords:
                if kw.arg == "site":
                    site_arg = kw.value
            if len(node.args) >= 3:
                site_arg = node.args[2]
            if site_arg is not None and not (
                    isinstance(site_arg, ast.Constant) and
                    site_arg.value in cfg.known_sites):
                findings.append(Finding(
                    "device-unknown-site", ctx.relpath, node.lineno,
                    f"{last_name(node.func)} site= must be a string "
                    f"literal from {sorted(cfg.known_sites)}"))
                nodes.append(node)
            continue
        kind = None
        if isinstance(node, (ast.Attribute, ast.Name)) and \
                isinstance(getattr(node, "ctx", None), ast.Load):
            parent = ctx.parent(node)
            if isinstance(parent, (ast.Attribute,)):
                continue                # inner part of a longer dotted path
            kind = _device_ref_kind(node, ctx)
            if kind is None:
                continue
            if isinstance(parent, ast.Call) and parent.func is node:
                node_for_line = parent
            else:
                node_for_line = node
        else:
            continue

        fn = _effective_function(ctx, node)
        if not in_seam:
            if kind == "jit" and fn is None:
                continue                # module-level kernel definition
            findings.append(Finding(
                "device-raw-call", ctx.relpath, node_for_line.lineno,
                f"raw {dotted(node) or node.attr} outside the seam "
                f"allowlist — route through the jit_exec seam "
                f"(seam_device_put / seam_jit / device_fault_point) so "
                f"faults inject and the plane breaker observes it"))
            nodes.append(node_for_line)
            continue

        # --- inside a seam module: dominance by the fault seam ------------
        if fn is None:
            continue                    # module-level kernel definition
        if kind == "block":
            continue                    # sync discipline is host-sync's rule
        want = cfg.upload_sites if kind == "device_put" \
            else ("compile",)
        sites = _fault_sites_before(ctx, cfg, fn, node_for_line.lineno)
        if any(s in want for s in sites):
            continue
        if _wrapper_forwards_guard(cfg, fn, node_for_line.lineno) or \
                _in_trampoline_closure(ctx, cfg, fn):
            continue
        findings.append(Finding(
            "device-unguarded", ctx.relpath, node_for_line.lineno,
            f"{dotted(node)} in {fn.qualname}() is not dominated by "
            f"device_fault_point({'/'.join(want)}) — this device "
            f"touchpoint is invisible to fault injection and the "
            f"plane breaker"))
        nodes.append(node_for_line)

    # .lower(...).compile() chains in seam modules count as compiles
    if in_seam:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "compile" and \
                    "jit" in ast.dump(node.func.value)[:400]:
                fn = ctx.enclosing_function(node)
                if fn is None:
                    continue
                sites = _fault_sites_before(ctx, cfg, fn, node.lineno)
                if "compile" in sites or \
                        _in_trampoline_closure(ctx, cfg, fn):
                    continue
                findings.append(Finding(
                    "device-unguarded", ctx.relpath, node.lineno,
                    f"program compile in {fn.qualname}() is not "
                    f"dominated by device_fault_point(compile)"))
                nodes.append(node)
    return apply_suppressions(ctx, findings, nodes)
