from elasticsearch_tpu.analysis.analyzers import (
    AnalysisRegistry,
    Analyzer,
    Token,
    standard_tokenizer,
    whitespace_tokenizer,
)

__all__ = [
    "AnalysisRegistry",
    "Analyzer",
    "Token",
    "standard_tokenizer",
    "whitespace_tokenizer",
]
