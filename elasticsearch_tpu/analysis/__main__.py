"""``python -m elasticsearch_tpu.analysis`` → plane-lint (see
elasticsearch_tpu/analysis/lint/)."""

import sys

from elasticsearch_tpu.analysis.lint.cli import main

sys.exit(main())
