#!/usr/bin/env python
"""Benchmark: MS-MARCO-shaped BM25 top-1000, QPS per chip.

The driver-defined headline metric (BASELINE.json): batched BM25 top-k over
a passage-scale corpus on one chip, vs a CPU lexical-engine baseline.

Corpus: synthetic Zipf corpus shaped like MS-MARCO passages (default 200k
docs — overridable via BENCH_DOCS — ~56 tokens/doc, 30k vocab). Queries:
4-term Zipf-sampled batches (BENCH_BATCH, default 64).

CPU baseline: scipy CSR eager-impact scoring (the BM25S formulation,
PAPERS.md — generally *faster* than Lucene's postings iteration, so the
ratio is conservative) + argpartition top-k.

Prints exactly ONE JSON line:
  {"metric": ..., "value": QPS, "unit": "qps", "vs_baseline": ratio}
Everything else goes to stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def pick_platform() -> str:
    """Probe the default JAX backend in a subprocess (the axon TPU tunnel can
    block indefinitely when down). Retries with backoff and reports the real
    failure before any CPU fallback — round 1 silently benched CPU and
    recorded 0.006x; never again."""
    if os.environ.get("BENCH_PLATFORM"):
        return os.environ["BENCH_PLATFORM"]
    probe = ("import jax,sys;"
             "d=jax.devices()[0];"
             "sys.stdout.write(d.platform)")
    timeouts = (300, 420, 600)
    for attempt, t in enumerate(timeouts, 1):
        if attempt > 1:
            time.sleep(min(30 * (attempt - 1), 90))
        try:
            out = subprocess.run([sys.executable, "-c", probe], timeout=t,
                                 capture_output=True, text=True)
            if out.returncode == 0 and out.stdout.strip():
                log(f"[bench] backend probe ok (attempt {attempt}): "
                    f"platform={out.stdout.strip()}")
                return "default"
            log(f"[bench] backend probe attempt {attempt} failed "
                f"rc={out.returncode}\n--- stderr tail ---\n"
                + "\n".join(out.stderr.strip().splitlines()[-15:]))
        except subprocess.TimeoutExpired:
            log(f"[bench] backend probe attempt {attempt} timed out "
                f"after {t}s (device init hang — TPU tunnel down?)")
    log("[bench] default backend UNAVAILABLE after "
        f"{len(timeouts)} attempts; falling back to CPU — "
        "the recorded number is NOT a TPU result")
    return "cpu"


def make_corpus(rng, n_docs: int, vocab: int, mean_len: int, max_unique: int):
    """Vectorized Zipf corpus directly in packed column form."""
    lens = np.clip(rng.poisson(mean_len, n_docs), 8, 112).astype(np.int32)
    L = int(lens.max())
    # zipf-ish: sample from a power-law over the vocab
    ranks = (rng.pareto(1.1, size=(n_docs, L)) + 1).astype(np.float64)
    toks = np.minimum((ranks * 3).astype(np.int64), vocab - 1).astype(np.int32)
    mask = np.arange(L)[None, :] < lens[:, None]
    toks = np.where(mask, toks, -1)

    # unique terms + counts per row (vectorized)
    order = np.argsort(toks, axis=1, kind="stable")
    st = np.take_along_axis(toks, order, axis=1)
    new = np.ones_like(st, dtype=bool)
    new[:, 1:] = st[:, 1:] != st[:, :-1]
    new &= st >= 0
    uidx = np.cumsum(new, axis=1) - 1              # unique slot per token
    U = int(new.sum(axis=1).max())
    U = min(U, max_unique)
    uterms = np.full((n_docs, U), -1, np.int32)
    utf = np.zeros((n_docs, U), np.float32)
    rows = np.repeat(np.arange(n_docs), L).reshape(n_docs, L)
    valid = (st >= 0) & (uidx < U)
    np.add.at(utf, (rows[valid], uidx[valid]), 1.0)
    first = new & valid
    uterms[rows[first], uidx[first]] = st[first]

    df = np.zeros(vocab, np.int64)
    np.add.at(df, uterms[uterms >= 0], 1)
    return uterms, utf, lens, df


def make_queries(rng, n_queries: int, vocab: int, terms: int, df):
    """Query terms sampled from the corpus distribution (common + rare mix)."""
    present = np.nonzero(df > 0)[0]
    w = df[present].astype(np.float64)
    w /= w.sum()
    qtids = rng.choice(present, size=(n_queries, terms), p=w).astype(np.int32)
    return qtids


def main() -> int:
    n_docs = int(os.environ.get("BENCH_DOCS", 200_000))
    vocab = int(os.environ.get("BENCH_VOCAB", 30_000))
    n_queries = int(os.environ.get("BENCH_QUERIES", 512))
    batch = int(os.environ.get("BENCH_BATCH", 64))
    k = int(os.environ.get("BENCH_K", 1000))
    terms = int(os.environ.get("BENCH_TERMS", 4))
    max_unique = int(os.environ.get("BENCH_MAX_UNIQUE", 80))

    platform = pick_platform()
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from elasticsearch_tpu.models.bm25 import bm25_topk_batch
    from elasticsearch_tpu.ops.similarity import BM25Params

    dev = jax.devices()[0]
    log(f"[bench] device: {dev.platform} ({dev})  corpus={n_docs} docs, "
        f"vocab={vocab}, k={k}, batch={batch}")

    rng = np.random.default_rng(1234)
    t0 = time.perf_counter()
    uterms, utf, lens, df = make_corpus(rng, n_docs, vocab, 56, max_unique)
    avgdl = float(lens.sum()) / n_docs
    log(f"[bench] corpus built in {time.perf_counter()-t0:.1f}s  "
        f"avgdl={avgdl:.1f} U={uterms.shape[1]}")

    qtids_all = make_queries(rng, n_queries, vocab, terms, df)
    p = BM25Params()
    idf_table = np.where(
        df > 0, np.log1p((n_docs - df + 0.5) / (df + 0.5)), 0.0
    ).astype(np.float32)
    qidf_all = idf_table[qtids_all]

    # ---- CPU baseline: BM25S-style eager CSR impact scoring ---------------
    cpu_queries = min(n_queries, int(os.environ.get("BENCH_CPU_QUERIES", 64)))
    from scipy import sparse
    valid = uterms >= 0
    rows = np.repeat(np.arange(n_docs), uterms.shape[1]).reshape(uterms.shape)
    norm = p.k1 * (1 - p.b + p.b * lens.astype(np.float64) / avgdl)
    impact = (utf * (p.k1 + 1) / (utf + norm[:, None])).astype(np.float32)
    mat = sparse.csc_matrix(
        (impact[valid], (rows[valid], uterms[valid])),
        shape=(n_docs, vocab))
    t0 = time.perf_counter()
    for qi in range(cpu_queries):
        scores = np.zeros(n_docs, np.float32)
        for t, w in zip(qtids_all[qi], qidf_all[qi]):
            col = mat.getcol(int(t))
            scores[col.indices] += w * col.data
        top = np.argpartition(scores, -k)[-k:] if n_docs > k else \
            np.arange(n_docs)
        top[np.argsort(-scores[top], kind="stable")]
    cpu_time = time.perf_counter() - t0
    cpu_qps = cpu_queries / cpu_time
    log(f"[bench] CPU baseline: {cpu_qps:.1f} QPS "
        f"({cpu_time*1000/cpu_queries:.2f} ms/query)")

    # ---- device run --------------------------------------------------------
    # pad rows to a power-of-2 bucket (engine segments are bucketized the
    # same way; the slots kernel wants block-divisible row counts)
    n_pad = 1 << (n_docs - 1).bit_length()
    if n_pad != n_docs:
        pad = n_pad - n_docs
        uterms = np.pad(uterms, ((0, pad), (0, 0)), constant_values=-1)
        utf = np.pad(utf, ((0, pad), (0, 0)))
        lens_p = np.pad(lens, (0, pad), constant_values=1)
    else:
        lens_p = lens
    live_np = np.zeros(n_pad, bool)
    live_np[:n_docs] = True

    d_uterms = jax.device_put(jnp.asarray(uterms), dev)
    d_utf = jax.device_put(jnp.asarray(utf), dev)
    d_len = jax.device_put(jnp.asarray(lens_p), dev)
    d_live = jax.device_put(jnp.asarray(live_np), dev)

    from elasticsearch_tpu.ops import postings as postings_ops

    kernels = os.environ.get("BENCH_KERNEL", "slots,forward,csr").split(",")
    n_batches = max(n_queries // batch, 1)
    csr_index = None
    if "csr" in kernels:
        t0 = time.perf_counter()
        csr_index = postings_ops.PostingsIndex.from_forward(
            uterms[:n_docs], utf[:n_docs], vocab)
        log(f"[bench] CSR inversion built in {time.perf_counter()-t0:.1f}s "
            f"(nnz={csr_index.docs.shape[0]})")

    # fixed shapes across batches so the timed loop hits ONE compiled
    # program per kernel (batch-dependent S/E padding would otherwise
    # recompile inside the timing window and record compile as throughput)
    s_fixed = ((batch * terms + 31) // 32) * 32
    plans = [postings_ops.plan_batch(qtids_all[i*batch:(i+1)*batch],
                                     qidf_all[i*batch:(i+1)*batch],
                                     vocab, s_total=s_fixed)
             for i in range(n_batches)]
    csr_gathers = None
    if "csr" in kernels and csr_index is not None:
        raw = [csr_index.gather_batch(t_, s_fixed, pad_to=1)
               for t_, _ in plans]
        e_fixed = max(es.shape[0] for es, _, _ in raw)
        csr_gathers = [(np.pad(es, (0, e_fixed - es.shape[0]),
                               constant_values=s_fixed),
                        np.pad(ed, (0, e_fixed - ed.shape[0])),
                        np.pad(etf, (0, e_fixed - etf.shape[0])))
                       for es, ed, etf in raw]
        log(f"[bench] csr batch entries padded to E={e_fixed}")

    def make_runner(kernel: str):
        """→ per-batch callable(i) → (scores, docs) device arrays."""
        if kernel == "forward":
            return lambda i: bm25_topk_batch(
                d_uterms, d_utf, d_len, d_live,
                jax.device_put(jnp.asarray(qtids_all[i*batch:(i+1)*batch]), dev),
                jax.device_put(jnp.asarray(qidf_all[i*batch:(i+1)*batch]), dev),
                np.float32(avgdl), k, p.k1, p.b)
        if kernel == "slots":
            def run(i):
                table, w = plans[i]
                return postings_ops.bm25_topk_batch_slots(
                    d_uterms, d_utf, d_len, d_live,
                    jax.device_put(jnp.asarray(table), dev),
                    jax.device_put(jnp.asarray(w), dev),
                    np.float32(avgdl), k, p.k1, p.b)
            return run
        if kernel == "csr":
            def run(i):
                es, ed, etf = csr_gathers[i]
                wp = np.pad(plans[i][1], ((0, 0), (0, 1)))  # zero pad slot
                return postings_ops.bm25_topk_batch_csr(
                    jax.device_put(jnp.asarray(es), dev),
                    jax.device_put(jnp.asarray(ed), dev),
                    jax.device_put(jnp.asarray(etf), dev),
                    d_len, d_live,
                    jax.device_put(jnp.asarray(wp), dev),
                    np.float32(avgdl), n_pad, k, p.k1, p.b)
            return run
        raise ValueError(f"unknown kernel [{kernel}]")

    results = {}
    outs0 = {}
    for kernel in kernels:
        run_batch = make_runner(kernel)
        t0 = time.perf_counter()
        s, d = run_batch(0)
        s.block_until_ready()
        compile_s = time.perf_counter() - t0
        outs0[kernel] = (np.asarray(s), np.asarray(d))
        # steady-state: time one batch; adaptively decide how many to run
        t0 = time.perf_counter()
        s, d = run_batch(0)
        s.block_until_ready()
        per_batch = time.perf_counter() - t0
        todo = n_batches if per_batch < 2.0 else 1
        t0 = time.perf_counter()
        last = None
        for i in range(todo):
            last = run_batch(i)
        last[0].block_until_ready()
        dt = time.perf_counter() - t0
        qps = (todo * batch) / dt
        results[kernel] = {"qps": round(qps, 2),
                           "ms_per_batch": round(dt / todo * 1000, 2),
                           "compile_s": round(compile_s, 1)}
        log(f"[bench] kernel={kernel}: {qps:.1f} QPS "
            f"({dt/todo*1000:.1f} ms / {batch}-query batch, "
            f"compile {compile_s:.1f}s)")

    best = max(results, key=lambda kr: results[kr]["qps"])
    qps = results[best]["qps"]
    log(f"[bench] best kernel: {best}")

    # recall sanity: device top-k must match CPU scoring for a few queries
    s0, d0 = outs0[best][0][0], outs0[best][1][0]
    ref_scores = np.zeros(n_docs, np.float32)
    for t, w in zip(qtids_all[0], qidf_all[0]):
        col = mat.getcol(int(t))
        ref_scores[col.indices] += w * col.data
    kk = min(k, int((ref_scores > 0).sum()))
    ref_top = np.sort(ref_scores)[::-1][:kk]
    got = s0[d0 >= 0][:kk]
    recall_ok = np.allclose(np.sort(got)[::-1][:kk], ref_top, rtol=2e-4,
                            atol=1e-5)
    log(f"[bench] recall parity vs CPU scoring: {recall_ok}")

    print(json.dumps({
        "metric": "bm25_top1000_qps_per_chip",
        "value": round(qps, 2),
        "unit": "qps",
        "vs_baseline": round(qps / cpu_qps, 3),
        "recall_ok": bool(recall_ok),
        "device": f"{dev.platform} ({dev})",
        "n_docs": n_docs,
        "cpu_baseline_qps": round(cpu_qps, 2),
        "kernel": best,
        "kernels": results,
    }))
    # the parity check gates the metric: a fast-but-wrong result must not
    # be recorded as a pass
    return 0 if recall_ok else 1


if __name__ == "__main__":
    sys.exit(main())
